"""Second C-API surface batch (the functions added for full c_api.h
parity — reference patterns: tests/c_api_test/test_.py CSC round-trip,
fast single-row init, eval names, leaf get/set, merge, reset)."""

import numpy as np
import pytest
import scipy.sparse as sp

import lightgbm_tpu.capi as capi


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(11)
    X = rng.randn(500, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0.2).astype(np.float64)
    return X, y


@pytest.fixture(scope="module")
def booster(data):
    X, y = data
    _, dh = capi.LGBM_DatasetCreateFromMat(
        X, "objective=binary verbosity=-1", label=y)
    _, bh = capi.LGBM_BoosterCreate(
        dh, "objective=binary num_leaves=15 verbosity=-1 metric=binary_logloss,auc")
    for _ in range(8):
        capi.LGBM_BoosterUpdateOneIter(bh)
    return bh


def test_csc_dataset_and_predict(data):
    X, y = data
    csc = sp.csc_matrix(X)
    code, dh = capi.LGBM_DatasetCreateFromCSC(
        csc, "objective=binary verbosity=-1 min_data_in_bin=1", label=y)
    assert code == 0
    assert capi.LGBM_DatasetGetNumData(dh)[1] == 500
    _, bh = capi.LGBM_BoosterCreate(
        dh, "objective=binary num_leaves=15 verbosity=-1")
    for _ in range(5):
        capi.LGBM_BoosterUpdateOneIter(bh)
    _, p_csc = capi.LGBM_BoosterPredictForCSC(bh, csc)
    _, p_mat = capi.LGBM_BoosterPredictForMat(bh, X)
    np.testing.assert_allclose(p_csc, p_mat, rtol=1e-6)
    capi.LGBM_BoosterFree(bh)
    capi.LGBM_DatasetFree(dh)


def test_eval_names_counts_predict(booster):
    code, n = capi.LGBM_BoosterGetEvalCounts(booster)
    assert code == 0 and n == 2
    code, names = capi.LGBM_BoosterGetEvalNames(booster)
    assert set(names) == {"binary_logloss", "auc"}
    code, npred = capi.LGBM_BoosterGetNumPredict(booster, 0)
    assert (code, npred) == (0, 500)
    code, preds = capi.LGBM_BoosterGetPredict(booster, 0)
    assert preds.shape == (500,)
    assert np.all((preds >= 0) & (preds <= 1))   # transformed probs


def test_leaf_get_set(booster, data):
    X, _ = data
    code, v = capi.LGBM_BoosterGetLeafValue(booster, 0, 0)
    assert code == 0
    before = capi.LGBM_BoosterPredictForMat(booster, X)[1]
    capi.LGBM_BoosterSetLeafValue(booster, 0, 0, v + 1.0)
    after = capi.LGBM_BoosterPredictForMat(booster, X)[1]
    assert not np.allclose(before, after)
    capi.LGBM_BoosterSetLeafValue(booster, 0, 0, v)   # restore
    restored = capi.LGBM_BoosterPredictForMat(booster, X)[1]
    np.testing.assert_allclose(restored, before, rtol=1e-6)
    assert capi.LGBM_BoosterGetLeafValue(booster, 0, 0)[1] == pytest.approx(v)


def test_bounds_linear_calcnum(booster):
    _, lo = capi.LGBM_BoosterGetLowerBoundValue(booster)
    _, hi = capi.LGBM_BoosterGetUpperBoundValue(booster)
    assert lo < hi
    assert capi.LGBM_BoosterGetLinear(booster)[1] == 0
    assert capi.LGBM_BoosterCalcNumPredict(booster, 7, 0)[1] == 7
    assert capi.LGBM_BoosterCalcNumPredict(
        booster, 7, capi.C_API_PREDICT_LEAF_INDEX)[1] == 7 * 8
    assert capi.LGBM_BoosterCalcNumPredict(
        booster, 3, capi.C_API_PREDICT_CONTRIB)[1] == 3 * 7


def test_fast_single_row(booster, data):
    X, _ = data
    _, fc = capi.LGBM_BoosterPredictForMatSingleRowFastInit(
        booster, ncol=X.shape[1])
    _, p = capi.LGBM_BoosterPredictForMatSingleRowFast(fc, X[3])
    _, ref = capi.LGBM_BoosterPredictForMat(booster, X[3:4])
    assert p == pytest.approx(np.asarray(ref)[0])
    capi.LGBM_FastConfigFree(fc)

    _, fc2 = capi.LGBM_BoosterPredictForCSRSingleRowFastInit(
        booster, num_col=X.shape[1])
    row = sp.csr_matrix(X[5:6])
    _, p2 = capi.LGBM_BoosterPredictForCSRSingleRowFast(fc2, row)
    assert p2 == pytest.approx(np.asarray(
        capi.LGBM_BoosterPredictForMat(booster, X[5:6])[1])[0])
    # (indices, values) form
    nz = np.nonzero(X[5])[0]
    _, p3 = capi.LGBM_BoosterPredictForCSRSingleRowFast(
        fc2, (nz, X[5][nz]))
    assert p3 == pytest.approx(p2)
    capi.LGBM_FastConfigFree(fc2)


def test_predict_mats_and_sparse_contrib(booster, data):
    X, _ = data
    _, pm = capi.LGBM_BoosterPredictForMats(booster, [X[0], X[1], X[2]])
    _, ref = capi.LGBM_BoosterPredictForMat(booster, X[:3])
    np.testing.assert_allclose(pm, ref, rtol=1e-6)

    csr = sp.csr_matrix(X[:50])
    _, sparse = capi.LGBM_BoosterPredictSparseOutput(
        booster, csr, capi.C_API_PREDICT_CONTRIB)
    dense = capi.LGBM_BoosterPredictForCSR(
        booster, csr, capi.C_API_PREDICT_CONTRIB)[1]
    np.testing.assert_allclose(np.asarray(sparse.todense()), dense,
                               rtol=1e-6, atol=1e-9)
    assert capi.LGBM_BoosterFreePredictSparse()[0] == 0


def test_merge_and_shuffle(data):
    X, y = data
    def train(rounds, seed):
        _, dh = capi.LGBM_DatasetCreateFromMat(
            X, f"objective=binary verbosity=-1 seed={seed}", label=y)
        _, bh = capi.LGBM_BoosterCreate(
            dh, f"objective=binary num_leaves=7 verbosity=-1 seed={seed}")
        for _ in range(rounds):
            capi.LGBM_BoosterUpdateOneIter(bh)
        return bh
    a, b = train(4, 1), train(3, 2)
    capi.LGBM_BoosterMerge(a, b)
    assert capi.LGBM_BoosterNumberOfTotalModel(a)[1] == 7
    pr = capi.LGBM_BoosterPredictForMat(a, X)[1]
    assert np.all(np.isfinite(pr))
    # shuffle changes tree order but not the (additive) predictions
    capi.LGBM_BoosterShuffleModels(a, 0, -1)
    np.testing.assert_allclose(capi.LGBM_BoosterPredictForMat(a, X)[1], pr,
                               rtol=1e-5, atol=1e-7)


def test_reset_training_data(data):
    X, y = data
    rng = np.random.RandomState(3)
    X2 = rng.randn(300, 6)
    y2 = (X2[:, 0] + 0.5 * X2[:, 1] > 0.2).astype(np.float64)
    _, dh = capi.LGBM_DatasetCreateFromMat(
        X, "objective=binary verbosity=-1", label=y)
    _, bh = capi.LGBM_BoosterCreate(
        dh, "objective=binary num_leaves=7 verbosity=-1 metric=binary_logloss")
    for _ in range(4):
        capi.LGBM_BoosterUpdateOneIter(bh)
    _, dh2 = capi.LGBM_DatasetCreateFromMat(
        X2, "objective=binary verbosity=-1", label=y2, reference=dh)
    assert capi.LGBM_BoosterResetTrainingData(bh, dh2)[0] == 0
    # model kept; training continues on the NEW data
    assert capi.LGBM_BoosterNumberOfTotalModel(bh)[1] == 4
    assert capi.LGBM_BoosterGetNumPredict(bh, 0)[1] == 300
    for _ in range(4):
        capi.LGBM_BoosterUpdateOneIter(bh)
    assert capi.LGBM_BoosterNumberOfTotalModel(bh)[1] == 8
    pr = capi.LGBM_BoosterPredictForMat(bh, X2)[1]
    ll = -np.mean(y2 * np.log(np.clip(pr, 1e-9, 1)) +
                  (1 - y2) * np.log(np.clip(1 - pr, 1e-9, 1)))
    assert ll < 0.6


def test_dataset_extras(data, tmp_path):
    X, y = data
    # feature names set/get
    _, dh = capi.LGBM_DatasetCreateFromMat(
        X, "objective=binary verbosity=-1", label=y)
    names = [f"f{i}" for i in range(6)]
    capi.LGBM_DatasetSetFeatureNames(dh, names)
    assert capi.LGBM_DatasetGetFeatureNames(dh)[1] == names
    # dump text
    path = str(tmp_path / "dump.txt")
    capi.LGBM_DatasetDumpText(dh, path)
    head = open(path).read().splitlines()
    assert head[0] == "num_data: 500" and "f3" in head[2]
    # param checking
    assert capi.LGBM_DatasetUpdateParamChecking(
        "max_bin=255 learning_rate=0.1", "max_bin=255 learning_rate=0.2")[0] == 0
    with pytest.raises(ValueError):
        capi.LGBM_DatasetUpdateParamChecking("max_bin=255", "max_bin=63")

    # mats create == mat create
    _, dh2 = capi.LGBM_DatasetCreateFromMats(
        [X[:200], X[200:]], "objective=binary verbosity=-1", label=y)
    assert capi.LGBM_DatasetGetNumData(dh2)[1] == 500

    # CSR-func create
    csr = sp.csr_matrix(X)
    def get_row(i):
        lo, hi = csr.indptr[i], csr.indptr[i + 1]
        return csr.indices[lo:hi], csr.data[lo:hi]
    _, dh3 = capi.LGBM_DatasetCreateFromCSRFunc(
        get_row, 500, 6, "objective=binary verbosity=-1 min_data_in_bin=1",
        label=y)
    assert capi.LGBM_DatasetGetNumData(dh3)[1] == 500

    # add features from (needs retained raw data)
    _, a = capi.LGBM_DatasetCreateFromMat(
        X[:, :3], "verbosity=-1 free_raw_data=false")
    _, b = capi.LGBM_DatasetCreateFromMat(
        X[:, 3:], "verbosity=-1 free_raw_data=false")
    assert capi.LGBM_DatasetAddFeaturesFrom(a, b)[0] == 0
    ds = capi._get(a)
    assert ds.data.shape == (500, 6)


def test_sampled_column_streaming(data):
    X, y = data
    cols = [X[:100, j].copy() for j in range(6)]
    idx = [np.arange(100)] * 6
    code, dh = capi.LGBM_DatasetCreateFromSampledColumn(
        cols, idx, 500, "objective=binary verbosity=-1")
    assert code == 0
    for lo in range(0, 500, 125):
        capi.LGBM_DatasetPushRows(dh, X[lo:lo + 125], lo)
    capi.LGBM_DatasetSetField(dh, "label", y)
    _, bh = capi.LGBM_BoosterCreate(
        dh, "objective=binary num_leaves=7 verbosity=-1")
    for _ in range(4):
        capi.LGBM_BoosterUpdateOneIter(bh)
    pr = capi.LGBM_BoosterPredictForMat(bh, X)[1]
    assert np.all(np.isfinite(pr))


def test_log_callback_and_set_error():
    lines = []
    capi.LGBM_RegisterLogCallback(lambda m: lines.append(m))
    from lightgbm_tpu.utils.log import log_info, set_verbosity
    set_verbosity(1)
    log_info("hello-capi")
    capi.LGBM_RegisterLogCallback(None)
    assert any("hello-capi" in ln for ln in lines)
    capi.LGBM_SetLastError("boom")
    assert capi.LGBM_GetLastError() == "boom"


def test_network_with_functions_single():
    assert capi.LGBM_NetworkInitWithFunctions(1, 0)[0] == 0
    with pytest.raises(NotImplementedError):
        capi.LGBM_NetworkInitWithFunctions(2, 0)
