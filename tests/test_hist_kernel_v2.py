"""Histogram kernel v2 (ISSUE 8): interpret-mode tier-1 coverage of the
four Pallas kernels — DMA pipeline vs BlockSpec vs 4-bit-packed bins —
against the XLA reference impls, plus the vmap-to-grid batching rule,
the pad_rows() error contract, the packed4 XLA scatter, the autotune
disk cache and the hist_kernel telemetry site.

Shapes are deliberately tiny and SHARED across tests (the interpret
kernels compile once per (shape, variant) and the jit cache is
process-wide), keeping the file cheap inside the tier-1 budget."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.ops.histogram import build_histogram, build_histogram_leaves
from lightgbm_tpu.ops.histogram_pallas import (
    LEAF_CHANNELS, Q_LEAF_CHANNELS, build_histogram_pallas,
    build_histogram_pallas_leaves, build_histogram_pallas_leaves_q8,
    pack_bins4, pack_weights8, pad_rows, unpack_bins4,
    wave_row_update_pallas, wave_trial_channels_pallas)

N, F = 4096, 5  # one exact row block — the boundary shape


def _data(n=N, f=F, B=16, seed=0):
    rng = np.random.RandomState(seed)
    bins = rng.randint(0, B, (n, f)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.abs(rng.randn(n)).astype(np.float32)
    # masked rows (w=0) must contribute nothing
    mask = (rng.rand(n) > 0.3).astype(np.float32)
    return bins, grad, hess, mask


# -- single-leaf kernel: every variant vs the XLA segment reference ----------

@pytest.mark.parametrize("B", [16, 64, 255])
def test_single_kernel_variants_vs_reference(B):
    bins, grad, hess, mask = _data(B=B)
    bt = jnp.asarray(bins.T.copy())
    g, h, m = map(jnp.asarray, (grad, hess, mask))
    ref = np.asarray(build_histogram(jnp.asarray(bins), g, h, m,
                                     num_bins=B, impl="segment"))
    scale = max(1.0, np.abs(ref).max())
    variants = [dict(pipeline="blockspec"), dict(pipeline="dma")]
    if B <= 16:
        variants.append(dict(bins_packed=True))
    outs = {}
    for kw in variants:
        src = pack_bins4(bt) if kw.get("bins_packed") else bt
        got = np.asarray(build_histogram_pallas(src, g, h, m,
                                                num_bins=B, **kw))
        name = "packed" if kw.get("bins_packed") else kw["pipeline"]
        outs[name] = got
        # f32 hi/lo exactness contract vs the f32 reference
        assert np.abs(got - ref).max() / scale < 1e-5, name
        # the count channel sums exact small integers — bitwise in any
        # accumulation order
        np.testing.assert_array_equal(got[..., 2], ref[..., 2], err_msg=name)


def test_single_kernel_n_plus_one_raises():
    bins, grad, hess, mask = _data(n=N + 1)
    with pytest.raises(ValueError, match="pad_rows"):
        build_histogram_pallas(jnp.asarray(bins.T.copy()),
                               jnp.asarray(grad), jnp.asarray(hess),
                               jnp.asarray(mask), num_bins=16)
    # row-aligned operand mismatch is caught by name
    bins, grad, hess, mask = _data()
    with pytest.raises(ValueError, match="grad"):
        build_histogram_pallas(jnp.asarray(bins.T.copy()),
                               jnp.asarray(grad[: N // 2]),
                               jnp.asarray(hess), jnp.asarray(mask),
                               num_bins=16)


def test_single_kernel_pad_boundary():
    """N=block data padded to 2 blocks with w=0 rows == unpadded build."""
    bins, grad, hess, mask = _data(B=16)
    bt = jnp.asarray(bins.T.copy())
    base = np.asarray(build_histogram_pallas(
        bt, jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(mask),
        num_bins=16))
    n2 = pad_rows(N + 1)
    assert n2 == 2 * N
    bp = jnp.asarray(np.pad(bins, ((0, n2 - N), (0, 0))).T.copy())
    padded = np.asarray(build_histogram_pallas(
        bp, jnp.asarray(np.pad(grad, (0, n2 - N))),
        jnp.asarray(np.pad(hess, (0, n2 - N))),
        jnp.asarray(np.pad(mask, (0, n2 - N))), num_bins=16))
    np.testing.assert_allclose(padded, base, rtol=1e-6, atol=1e-6)


def test_pack_bins4_roundtrip():
    bins, *_ = _data(B=16)
    bt = jnp.asarray(bins.T.copy())
    np.testing.assert_array_equal(np.asarray(unpack_bins4(pack_bins4(bt))),
                                  bins.T)


# -- leaf-batched kernels ----------------------------------------------------

def test_leaves_kernel_variants_vs_reference():
    bins, grad, hess, mask = _data(B=16, f=6)
    rng = np.random.RandomState(1)
    ch = rng.randint(-1, LEAF_CHANNELS, N).astype(np.int32)
    bt = jnp.asarray(bins.T.copy())
    g, h, m, chd = map(jnp.asarray, (grad, hess, mask, ch))
    w8 = pack_weights8(g, h, m)
    ref = np.asarray(build_histogram_leaves(
        jnp.asarray(bins), g, h, m, chd, num_channels=LEAF_CHANNELS,
        num_bins=16, impl="segment"))
    scale = max(1.0, np.abs(ref).max())
    for kw in [dict(pipeline="blockspec"), dict(pipeline="dma"),
               dict(bins_packed=True)]:
        src = pack_bins4(bt) if kw.get("bins_packed") else bt
        got = np.asarray(build_histogram_pallas_leaves(
            src, w8, chd, num_bins=16, **kw))
        assert np.abs(got - ref).max() / scale < 1e-5, kw
        np.testing.assert_array_equal(got[..., 2], ref[..., 2])


def test_q8_kernel_bitwise_across_variants():
    """Quantized path: int32 sums are exact — every pipeline/packing
    variant must agree bit-for-bit (the ISSUE 8 kernel contract)."""
    bins, _, _, mask = _data(B=16, f=6)
    rng = np.random.RandomState(2)
    wch = np.zeros((8, N), np.int8)
    act = (mask > 0)
    wch[0] = rng.randint(-127, 128, N) * act
    wch[1] = rng.randint(0, 128, N) * act
    wch[2] = act
    ch = rng.randint(-1, Q_LEAF_CHANNELS, N).astype(np.int8)
    bt = jnp.asarray(bins.T.copy())
    wchd, chd = jnp.asarray(wch), jnp.asarray(ch)
    base = np.asarray(build_histogram_pallas_leaves_q8(
        bt, wchd, chd, num_bins=16, pipeline="blockspec"))
    # reference check: histogram of channel 0 == per-leaf bincount
    want0 = np.zeros((16,), np.int64)
    sel = (ch == 0) & act
    for j in np.nonzero(sel)[0]:
        want0[bins[j, 0]] += int(wch[0, j])
    np.testing.assert_array_equal(base[0, 0, :, 0], want0)
    for kw in [dict(pipeline="dma"), dict(bins_packed=True)]:
        src = pack_bins4(bt) if kw.get("bins_packed") else bt
        got = np.asarray(build_histogram_pallas_leaves_q8(
            src, wchd, chd, num_bins=16, **kw))
        np.testing.assert_array_equal(got, base, err_msg=str(kw))


def test_leaves_kernels_bad_rows_raise():
    bins, grad, hess, mask = _data(B=16)
    bt = jnp.asarray(bins.T.copy())
    w8 = pack_weights8(*map(jnp.asarray, (grad, hess, mask)))
    ch = jnp.zeros((N,), jnp.int32)
    with pytest.raises(ValueError, match="pad_rows"):
        build_histogram_pallas_leaves(bt[:, :-8], w8[:, :-8], ch[:-8],
                                      num_bins=16)
    with pytest.raises(ValueError, match="wch"):
        build_histogram_pallas_leaves_q8(
            bt, jnp.zeros((8, N // 2), jnp.int8), ch.astype(jnp.int8),
            num_bins=16)


# -- row-update / trial-channel kernel ---------------------------------------

def test_row_update_dma_bitwise_and_trial():
    bins, *_ = _data(B=16, f=6)
    rng = np.random.RandomState(3)
    W = 4
    cols_w = jnp.asarray(bins.T[:W].copy())
    rl = jnp.asarray(rng.randint(0, 3, N).astype(np.int32))
    tab = jnp.asarray(np.stack([
        rng.randint(0, 16, W), np.full(W, -1), rng.randint(0, 2, W),
        rng.randint(0, 2, W), rng.randint(0, 3, W), np.arange(3, 3 + W),
        np.ones(W, int), np.zeros(W, int)]).astype(np.int32))
    rb, cb = wave_row_update_pallas(cols_w, rl, tab, pipeline="blockspec")
    rd, cd = wave_row_update_pallas(cols_w, rl, tab, pipeline="dma")
    np.testing.assert_array_equal(np.asarray(rb), np.asarray(rd))
    np.testing.assert_array_equal(np.asarray(cb), np.asarray(cd))
    # trial form commits nothing
    sel_leaves = tab[4]
    ch = wave_trial_channels_pallas(
        cols_w, rl, sel_leaves, tab[0], tab[1], tab[2] > 0, tab[3],
        tab[6] > 0, pipeline="dma")
    assert ch.shape == (N,)


# -- vmap-to-grid batching rule (the multitrain unlock) ----------------------

def test_vmap_batching_bitwise():
    """jax's pallas_call batching rule lowers the model axis to a
    leading grid dimension; per-lane results must be bit-identical to
    the unbatched calls for BOTH pipelines (lifts the multitrain
    segment|onehot gate, ROADMAP item 4)."""
    bins, _, _, mask = _data(B=16, f=6)
    rng = np.random.RandomState(4)
    M = 2
    wch = np.zeros((M, 8, N), np.int8)
    for k in range(M):
        wch[k, 0] = rng.randint(-50, 50, N)
        wch[k, 1] = rng.randint(0, 50, N)
        wch[k, 2] = 1
    ch = jnp.asarray(rng.randint(-1, Q_LEAF_CHANNELS, N).astype(np.int8))
    bt = jnp.asarray(bins.T.copy())
    wchb = jnp.asarray(wch)
    for pipe in ("blockspec", "dma"):
        def one(w_, pipe=pipe):
            return build_histogram_pallas_leaves_q8(bt, w_, ch,
                                                    num_bins=16,
                                                    pipeline=pipe)
        got = np.asarray(jax.jit(jax.vmap(one))(wchb))
        want = np.stack([np.asarray(one(wchb[k])) for k in range(M)])
        np.testing.assert_array_equal(got, want, err_msg=pipe)


# -- packed4 XLA scatter impl ------------------------------------------------

@pytest.mark.parametrize("f", [4, 5])
def test_packed4_xla_impl(f):
    bins, grad, hess, mask = _data(f=f, B=13)
    args = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask))
    ref = np.asarray(build_histogram(*args, num_bins=13, impl="segment"))
    got = np.asarray(build_histogram(*args, num_bins=13, impl="packed4"))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    with pytest.raises(ValueError, match="packed4"):
        build_histogram(*args, num_bins=64, impl="packed4")


# -- autotune: variant candidates + on-disk winner cache ---------------------

def test_autotune_disk_cache(tmp_path, monkeypatch):
    cache = tmp_path / "hist_autotune.json"
    monkeypatch.setenv("LGBM_TPU_AUTOTUNE_CACHE", str(cache))
    from lightgbm_tpu.learner import autotune
    X = np.random.RandomState(0).randint(0, 13, (N, 4)).astype(np.uint8)
    win = autotune.pick_hist_impl(X, 13, candidates=("segment", "packed4"),
                                  reps=2)
    assert win in ("segment", "packed4")
    assert cache.exists()
    # a fresh process (simulated: cleared in-memory caches) skips the
    # re-measurement pass and returns the persisted winner
    autotune._CACHE.clear()
    autotune._DISK_LOADED.clear()
    assert autotune.pick_hist_impl(
        X, 13, candidates=("segment", "packed4"), reps=2) == win


def test_autotune_default_candidates():
    from lightgbm_tpu.learner.autotune import default_candidates
    assert default_candidates("tpu", 255) == ("pallas", "pallas:blockspec",
                                              "onehot")
    assert "pallas:packed4" in default_candidates("tpu", 16)
    assert default_candidates("cpu", 16) == ("segment", "packed4")
    assert default_candidates("cpu", 255) == ("segment",)


def test_autotune_apply_winner():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.learner.autotune import apply_winner
    cfg = Config({})
    apply_winner(cfg, "pallas:blockspec")
    assert cfg.tpu_histogram_impl == "pallas"
    assert cfg.tpu_pallas_pipeline == "blockspec"
    assert cfg.tpu_hist_pack4 is False  # blockspec beat the packed DMA form
    # a PLAIN pallas winner beat the packed candidate: pack4 must clear,
    # else training would run the variant the probe just rejected
    apply_winner(cfg, "pallas")
    assert cfg.tpu_hist_pack4 is False
    assert cfg.tpu_pallas_pipeline == "dma"
    apply_winner(cfg, "pallas:packed4")
    assert cfg.tpu_hist_pack4 is True
    apply_winner(cfg, "segment")
    assert cfg.tpu_histogram_impl == "segment"


def test_pipeline_blockspec_disables_pack4():
    """Explicit tpu_pallas_pipeline=blockspec is the measured-dead-ends
    A/B knob: it must actually run the v1 layout, so pack4 (a DMA-only
    layout) turns off instead of silently forcing the pipeline back."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.learner.serial import SerialTreeLearner
    nb = np.full(4, 15, np.int32)
    flags = np.zeros(4, bool)
    mk = lambda pipe: SerialTreeLearner(
        Config({"num_leaves": 7, "tree_grow_mode": "wave",
                "tpu_histogram_impl": "pallas", "max_bin": 15,
                "tpu_pallas_pipeline": pipe, "verbosity": -1}),
        4, 15, nb, flags, flags)
    assert mk("auto").pack4 is True
    assert mk("dma").pack4 is True
    assert mk("blockspec").pack4 is False


# -- telemetry: the hist_kernel site -----------------------------------------

def test_hist_kernel_telemetry_site():
    from lightgbm_tpu.telemetry.train_record import (TrainRecord,
                                                     hist_kernel_snapshot)
    bins, grad, hess, mask = _data(B=16)
    rec = TrainRecord()
    build_histogram_pallas(jnp.asarray(bins.T.copy()), jnp.asarray(grad),
                           jnp.asarray(hess), jnp.asarray(mask),
                           num_bins=16, pipeline="dma")
    snap = rec.snapshot()
    sites = snap["hist_kernel"]
    assert any(k.startswith("ops/hist_kernel/single/dma") for k in sites)
    site = next(k for k in sites if k.startswith("ops/hist_kernel/single"))
    assert sites[site]["count"] >= 1
    assert sites[site]["bytes"] >= N * F  # at least the bin bytes
    assert hist_kernel_snapshot()  # process-wide tally holds it too


# -- Dataset 4-bit packed storage --------------------------------------------

def test_dataset_device_bins_packed4():
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.randn(500, 4)
    ds = lgb.Dataset(X, rng.rand(500), params={"max_bin": 15,
                                               "verbosity": -1})
    ds.construct(None)
    pk = ds.device_bins_packed4()
    n_pad = pad_rows(500)
    assert pk.shape == (ds.num_feature(), n_pad // 2)
    assert pk.dtype == jnp.uint8
    got = np.asarray(unpack_bins4(pk))[:, :500]
    np.testing.assert_array_equal(got, ds.X_binned.T)
    assert ds.device_bins_packed4() is pk  # cached
    ds255 = lgb.Dataset(X, rng.rand(500), params={"verbosity": -1})
    ds255.construct(None)
    if int(np.max(ds255.num_bins_per_feature)) > 16:
        with pytest.raises(ValueError, match="max_bin"):
            ds255.device_bins_packed4()
