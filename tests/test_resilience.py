"""Fault-tolerance suite (lightgbm_tpu.resilience, ISSUE 6): proves —
with injected faults, not assumptions — that

  * checkpoints are atomic on disk and resume is BIT-identical to an
    uninterrupted run (serial, quantized, and the DP-wave/reduce-scatter
    path) including bagging/feature-fraction RNG streams, eval history
    and early-stopping bookkeeping;
  * a SIGTERM mid-train drains the in-flight iteration and flushes one
    final checkpoint (in-process and real-subprocess);
  * a hard kill (``os._exit``, the chaos layer's ``kill_at_iter``)
    leaves a loadable snapshot ring behind;
  * restores against the wrong dataset / seeds fail loudly;
  * truncated model files raise typed :class:`ModelCorruptError`;
  * the micro-batcher sheds over-limit load, expires deadlines and
    fails queued work on close instead of hanging callers.
"""

import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import (Checkpoint, CheckpointError, ModelCorruptError,
                          TrainingPreempted, load_checkpoint)
from lightgbm_tpu.io_utils import atomic_write_bytes, atomic_write_text
from lightgbm_tpu.resilience.admission import (DeadlineExceeded,
                                               QueueFullError, ServerClosed)
from lightgbm_tpu.resilience.faults import InjectedFault, faults
from lightgbm_tpu.serve import MicroBatcher

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# bagging + feature sampling ON so a resume that mis-restores the RNG
# position cannot stay bit-identical by accident
PARAMS = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
          "verbosity": -1, "seed": 7, "bagging_fraction": 0.7,
          "bagging_freq": 1, "feature_fraction": 0.8}
ROUNDS = 8
CRASH_AT = 4


@pytest.fixture(autouse=True)
def _clear_faults():
    faults.clear()
    yield
    faults.clear()


def _data(seed=0, n=400, f=6):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(n) > 0).astype(np.float64)
    return X, y


def _crash_resume_roundtrip(tmp_path, extra_params, tag):
    """Train uninterrupted; train again with a crash injected at
    iteration CRASH_AT; resume; assert model text + predictions are
    bit-identical."""
    X, y = _data()
    P = {**PARAMS, **extra_params}
    full = lgb.train({**P, "checkpoint_dir": str(tmp_path / f"{tag}_full")},
                     lgb.Dataset(X, y), ROUNDS)
    ck = str(tmp_path / f"{tag}_ck")
    faults.configure(f"crash_at_iter={CRASH_AT}")
    with pytest.raises(InjectedFault):
        lgb.train({**P, "checkpoint_dir": ck}, lgb.Dataset(X, y), ROUNDS)
    faults.clear()
    resumed = lgb.train({**P, "checkpoint_dir": ck, "resume": "latest"},
                        lgb.Dataset(X, y), ROUNDS)
    # model_to_string excludes checkpoint_dir/resume from the params dump,
    # so the comparison is byte-for-byte with no normalization
    assert resumed.model_to_string() == full.model_to_string()
    np.testing.assert_array_equal(resumed.predict(X), full.predict(X))
    return full, resumed


# -- atomic writes -----------------------------------------------------------
def test_atomic_write_survives_writer_crash(tmp_path):
    path = str(tmp_path / "f.txt")
    atomic_write_text(path, "old content")

    def exploding_writer(fh):
        fh.write(b"half a new fi")
        raise RuntimeError("crash mid-write")

    with pytest.raises(RuntimeError):
        atomic_write_bytes(path, writer=exploding_writer)
    with open(path) as fh:
        assert fh.read() == "old content"
    assert os.listdir(tmp_path) == ["f.txt"]  # temp cleaned up


def test_atomic_write_replaces(tmp_path):
    path = str(tmp_path / "f.txt")
    atomic_write_text(path, "v1")
    atomic_write_text(path, "v2")
    with open(path) as fh:
        assert fh.read() == "v2"


# -- checkpoint bundle -------------------------------------------------------
def test_checkpoint_bundle_roundtrip():
    ck = Checkpoint(
        iteration=5, model_text="tree\nversion=v3\n",
        score=np.arange(6, dtype=np.float32),
        valid_names=["valid_0"],
        valid_scores=[np.ones(3, np.float32) * 0.25],
        eval_history={"valid_0": {"auc": [0.5, 0.6]}},
        early_stop=[{"rounds": 3, "first_metric_name": "auc",
                     "trackers": None}],
        rng_state={"seed": 7, "bagging_seed": 3},
        fingerprint={"num_data": 6, "data_crc32": 123},
        params={"objective": "binary"},
        prev_iter_leaves=[7])
    back = Checkpoint.from_bytes(ck.to_bytes())
    assert back.iteration == 5
    assert back.model_text == ck.model_text
    np.testing.assert_array_equal(back.score, ck.score)
    assert back.valid_names == ["valid_0"]
    np.testing.assert_array_equal(back.valid_scores[0], ck.valid_scores[0])
    assert back.eval_history == ck.eval_history
    assert back.early_stop == ck.early_stop
    assert back.rng_state == {"seed": 7, "bagging_seed": 3}
    assert back.fingerprint["data_crc32"] == 123
    assert back.prev_iter_leaves == [7]


def test_truncated_checkpoint_bundle_rejected():
    data = Checkpoint(iteration=1, model_text="tree\n",
                      score=np.zeros(4, np.float32)).to_bytes()
    with pytest.raises(CheckpointError, match="not a readable checkpoint"):
        Checkpoint.from_bytes(data[:len(data) // 2], source="half.npz")
    with pytest.raises(CheckpointError, match="garbage.npz"):
        Checkpoint.from_bytes(b"\x00garbage" * 10, source="garbage.npz")


def test_checkpoint_ring_bounded_and_latest(tmp_path):
    X, y = _data()
    ck = str(tmp_path / "ring")
    lgb.train({**PARAMS, "checkpoint_dir": ck, "snapshot_freq": 1,
               "checkpoint_keep": 2,
               # snapshot_freq also writes model-text snapshots; keep
               # them out of the process CWD
               "output_model": str(tmp_path / "model.txt")},
              lgb.Dataset(X, y), 6)
    names = sorted(os.listdir(ck))
    assert names == ["LATEST", "ckpt_iter00000005.npz",
                     "ckpt_iter00000006.npz"]
    assert load_checkpoint(ck).iteration == 6


def test_latest_pointer_falls_back_to_newest_ring_entry(tmp_path):
    X, y = _data()
    ck = str(tmp_path / "ring")
    lgb.train({**PARAMS, "checkpoint_dir": ck}, lgb.Dataset(X, y), 3)
    os.unlink(os.path.join(ck, "LATEST"))  # crash between write + repoint
    assert load_checkpoint(ck).iteration == 3


# -- crash / resume bit-identity ---------------------------------------------
def test_crash_resume_bit_identity_serial(tmp_path):
    _crash_resume_roundtrip(tmp_path, {}, "serial")


def test_crash_resume_bit_identity_quantized(tmp_path):
    _crash_resume_roundtrip(
        tmp_path, {"use_quantized_grad": True, "stochastic_rounding": True},
        "quant")


@pytest.mark.slow  # 8-device mesh compile; the CI chaos step runs it
def test_crash_resume_bit_identity_dp_wave(tmp_path):
    # the DP-wave reduce-scatter path on the virtual 8-device mesh
    # (PR 4's parity target); quantized so DP == serial is bit-exact
    _crash_resume_roundtrip(
        tmp_path,
        {"tree_learner": "data", "tree_grow_mode": "wave",
         "use_quantized_grad": True, "stochastic_rounding": False,
         "num_devices": 8},
        "dpwave")


def test_crash_resume_multiclass(tmp_path):
    X, _ = _data(n=300)
    rng = np.random.RandomState(3)
    y = rng.randint(0, 3, 300).astype(np.float64)
    P = {"objective": "multiclass", "num_class": 3, "num_leaves": 5,
         "verbosity": -1, "seed": 11}
    full = lgb.train(P, lgb.Dataset(X, y), 6)
    ck = str(tmp_path / "mc")
    faults.configure("crash_at_iter=3")
    with pytest.raises(InjectedFault):
        lgb.train({**P, "checkpoint_dir": ck}, lgb.Dataset(X, y), 6)
    faults.clear()
    resumed = lgb.train({**P, "checkpoint_dir": ck, "resume": "latest"},
                        lgb.Dataset(X, y), 6)
    # model_to_string excludes checkpoint_dir/resume from the params dump,
    # so the comparison is byte-for-byte with no normalization
    assert resumed.model_to_string() == full.model_to_string()
    np.testing.assert_array_equal(resumed.predict(X), full.predict(X))


def test_resume_restores_eval_history_and_early_stop(tmp_path):
    X, y = _data()
    Xv, yv = _data(seed=9, n=150)
    # share PARAMS' (num_leaves, N, F) shape so the grower compile is
    # reused across the file instead of paying a fresh jit here; also
    # exercises early-stop resume together with bagging state
    P = {**PARAMS, "early_stopping_round": 3, "metric": "binary_logloss"}

    def run(params, rounds, resume=False):
        ds = lgb.Dataset(X, y)
        dv = ds.create_valid(Xv, yv)
        hist = {}
        bst = lgb.train({**params, **({"resume": "latest"} if resume
                                      else {})}, ds, rounds,
                        valid_sets=[dv],
                        callbacks=[lgb.record_evaluation(hist)])
        return bst, hist

    full, hist_full = run(P, 30)
    ck = str(tmp_path / "es")
    run({**P, "checkpoint_dir": ck}, 5)
    resumed, hist_res = run({**P, "checkpoint_dir": ck}, 30, resume=True)
    assert resumed.best_iteration == full.best_iteration
    assert resumed.num_trees() == full.num_trees()
    assert hist_res == hist_full  # refilled across the preemption


def test_resume_latest_cold_start_trains_fresh(tmp_path):
    X, y = _data()
    bst = lgb.train({**PARAMS, "checkpoint_dir": str(tmp_path / "empty"),
                     "resume": "latest"}, lgb.Dataset(X, y), 5)
    assert bst.num_trees() == 5


# -- restore validation ------------------------------------------------------
def test_fingerprint_mismatch_rejected(tmp_path):
    X, y = _data()
    ck = str(tmp_path / "fp")
    lgb.train({**PARAMS, "checkpoint_dir": ck}, lgb.Dataset(X, y), 3)
    X2, y2 = _data(seed=5)  # different rows, same shape
    with pytest.raises(CheckpointError, match="does not match"):
        lgb.train({**PARAMS, "checkpoint_dir": ck, "resume": "latest"},
                  lgb.Dataset(X2, y2), 6)


def test_seed_change_rejected(tmp_path):
    X, y = _data()
    ck = str(tmp_path / "seed")
    lgb.train({**PARAMS, "checkpoint_dir": ck}, lgb.Dataset(X, y), 3)
    with pytest.raises(CheckpointError, match="RNG seed"):
        lgb.train({**PARAMS, "seed": 8, "checkpoint_dir": ck,
                   "resume": "latest"}, lgb.Dataset(X, y), 6)


def test_stopping_rounds_change_rejected(tmp_path):
    X, y = _data()
    Xv, yv = _data(seed=9, n=150)
    P = {**PARAMS, "metric": "binary_logloss"}

    def run(rounds_patience, resume=False, first_metric_only=False):
        ds = lgb.Dataset(X, y)
        lgb.train({**P, "checkpoint_dir": str(tmp_path / "esr"),
                   **({"resume": "latest"} if resume else {})},
                  ds, 6, valid_sets=[ds.create_valid(Xv, yv)],
                  callbacks=[lgb.early_stopping(
                      rounds_patience, first_metric_only=first_metric_only,
                      verbose=False)])

    run(10)
    with pytest.raises(CheckpointError, match="stopping_rounds"):
        run(5, resume=True)
    with pytest.raises(CheckpointError, match="first_metric_only"):
        run(10, resume=True, first_metric_only=True)


def test_atomic_write_concurrent_same_target(tmp_path):
    """Concurrent writers to one path must each publish a complete payload
    — never an interleaved hybrid — which requires per-call temp names."""
    target = str(tmp_path / "model.txt")
    payloads = [bytes([i]) * 4096 for i in range(8)]
    errs = []

    def write(p):
        try:
            for _ in range(20):
                atomic_write_bytes(target, p)
        except BaseException as e:  # pragma: no cover - failure path
            errs.append(e)

    threads = [threading.Thread(target=write, args=(p,)) for p in payloads]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    with open(target, "rb") as fh:
        data = fh.read()
    assert data in payloads  # one winner, intact
    assert not [f for f in os.listdir(tmp_path) if ".tmp." in f]


def test_objective_change_rejected(tmp_path):
    X, y = _data()
    ck = str(tmp_path / "obj")
    lgb.train({**PARAMS, "checkpoint_dir": ck}, lgb.Dataset(X, y), 3)
    with pytest.raises(CheckpointError, match="objective"):
        lgb.train({**PARAMS, "objective": "regression",
                   "checkpoint_dir": ck, "resume": "latest"},
                  lgb.Dataset(X, y), 6)


def test_dart_checkpoint_rejected(tmp_path):
    X, y = _data()
    ck = str(tmp_path / "dart")
    lgb.train({**PARAMS, "boosting": "dart", "checkpoint_dir": ck},
              lgb.Dataset(X, y), 3)
    with pytest.raises(ValueError, match="dart"):
        lgb.train({**PARAMS, "boosting": "dart", "checkpoint_dir": ck,
                   "resume": "latest"}, lgb.Dataset(X, y), 6)


# -- preemption (SIGTERM) ----------------------------------------------------
def test_sigterm_in_process_flushes_and_resumes(tmp_path):
    """A SIGTERM arriving mid-train (sent from a watchdog thread, the
    closest in-process analogue of a TPU preemption notice) drains the
    iteration, flushes a final checkpoint, raises TrainingPreempted —
    and the resumed run is bit-identical to one that never stopped."""
    X, y = _data()
    full = lgb.train(PARAMS, lgb.Dataset(X, y), ROUNDS)
    ck = str(tmp_path / "sig")
    fired = threading.Event()

    def kill_at(env):
        if env.iteration == CRASH_AT and not fired.is_set():
            fired.set()
            os.kill(os.getpid(), signal.SIGTERM)
    kill_at.before_iteration = True

    with pytest.raises(TrainingPreempted) as exc_info:
        lgb.train({**PARAMS, "checkpoint_dir": ck}, lgb.Dataset(X, y),
                  ROUNDS, callbacks=[kill_at])
    exc = exc_info.value
    assert exc.signum == signal.SIGTERM
    assert exc.checkpoint and os.path.exists(exc.checkpoint)
    # the in-flight iteration was drained, not abandoned
    assert load_checkpoint(ck).iteration == CRASH_AT + 1
    resumed = lgb.train({**PARAMS, "checkpoint_dir": ck, "resume": "latest"},
                        lgb.Dataset(X, y), ROUNDS)
    # model_to_string excludes checkpoint_dir/resume from the params dump,
    # so the comparison is byte-for-byte with no normalization
    assert resumed.model_to_string() == full.model_to_string()


_CHILD_COMMON = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import lightgbm_tpu as lgb
    lgb.set_verbosity(-1)
    rng = np.random.RandomState(0)
    X = rng.randn(400, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(400) > 0).astype(float)
    P = {{"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
          "verbosity": -1, "seed": 7, "bagging_fraction": 0.7,
          "bagging_freq": 1, "feature_fraction": 0.8,
          "checkpoint_dir": sys.argv[1]}}
""")


@pytest.mark.slow  # subprocess + jax import; the CI chaos step runs it
def test_sigterm_subprocess_flushes_checkpoint(tmp_path):
    """Real preemption shape: SIGTERM a separate training process, it
    exits AFTER flushing a loadable final checkpoint."""
    ck = str(tmp_path / "ck")
    script = _CHILD_COMMON.format(repo=REPO) + textwrap.dedent("""
        import time
        from lightgbm_tpu import TrainingPreempted
        def slow(env):
            if env.iteration == 1:
                print("TRAINING", flush=True)
            time.sleep(0.05)
        slow.before_iteration = True
        try:
            lgb.train(P, lgb.Dataset(X, y), 500, callbacks=[slow])
        except TrainingPreempted as exc:
            print("FLUSHED", exc.checkpoint, flush=True)
            sys.exit(43)
        sys.exit(0)
    """)
    proc = subprocess.Popen([sys.executable, "-c", script, ck],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            env={**os.environ, "JAX_PLATFORMS": "cpu"})
    try:
        # wait until the loop is demonstrably mid-train, then preempt
        line = ""
        for line in proc.stdout:
            if "TRAINING" in line:
                break
        assert "TRAINING" in line, "child never started training"
        proc.send_signal(signal.SIGTERM)
        out = proc.stdout.read()
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert rc == 43, f"child exited {rc}: {out}"
    assert "FLUSHED" in out
    ckpt = load_checkpoint(ck)
    assert 0 < ckpt.iteration < 500


@pytest.mark.slow  # subprocess + jax import; the CI chaos step runs it
def test_kill_at_iter_subprocess_leaves_resumable_ring(tmp_path):
    """The chaos layer's hard kill (os._exit mid-train, no flush, no
    atexit — a preempted/OOM-killed worker): the atomic ring written so
    far must be loadable and the resumed run bit-identical."""
    ck = str(tmp_path / "ck")
    script = _CHILD_COMMON.format(repo=REPO) + textwrap.dedent("""
        lgb.train(P, lgb.Dataset(X, y), 10)
        sys.exit(0)  # unreachable: the armed fault kills at iteration 6
    """)
    proc = subprocess.run(
        [sys.executable, "-c", script, ck], capture_output=True, text=True,
        timeout=240, env={**os.environ, "JAX_PLATFORMS": "cpu",
                          "LGBM_TPU_FAULTS": "kill_at_iter=6"})
    assert proc.returncode == 137, proc.stdout + proc.stderr
    ckpt = load_checkpoint(ck)
    assert ckpt.iteration == 6  # snapshots through the kill boundary

    # resume in THIS process against identically-built data
    rng = np.random.RandomState(0)
    X = rng.randn(400, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(400) > 0).astype(float)
    full = lgb.train(PARAMS, lgb.Dataset(X, y), 10)
    resumed = lgb.train({**PARAMS, "checkpoint_dir": ck, "resume": "latest"},
                        lgb.Dataset(X, y), 10)
    # model_to_string excludes checkpoint_dir/resume from the params dump,
    # so the comparison is byte-for-byte with no normalization
    assert resumed.model_to_string() == full.model_to_string()


# -- device-loss fault -------------------------------------------------------
def test_device_loss_fault_drives_cpu_fallback():
    from lightgbm_tpu.utils import backend
    saved = backend._resolved, backend._fallback_reason
    try:
        backend._reset_probe_for_tests()
        faults.configure("device_loss=1")
        assert backend.default_backend() == "cpu"
        assert "device lost" in (backend.fallback_reason() or "")
    finally:
        faults.clear()
        backend._resolved, backend._fallback_reason = saved


def test_fault_plan_env_parse():
    from lightgbm_tpu.resilience.faults import _parse_spec
    assert _parse_spec("crash_at_iter=3, kill_rank=1") == \
        {"crash_at_iter": 3, "kill_rank": 1}
    with pytest.raises(ValueError):
        _parse_spec("bogus")


# -- corrupt model files -----------------------------------------------------
def test_truncated_model_file_raises_typed_error(tmp_path):
    X, y = _data()
    bst = lgb.train(PARAMS, lgb.Dataset(X, y), 5)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    full = open(path, "rb").read()
    trunc = str(tmp_path / "trunc.txt")
    with open(trunc, "wb") as fh:
        fh.write(full[:len(full) // 2])  # crash-truncated snapshot
    with pytest.raises(ModelCorruptError) as exc_info:
        lgb.Booster(model_file=trunc)
    assert "trunc.txt" in str(exc_info.value)
    assert exc_info.value.offset >= 0
    # the intact file still loads
    assert lgb.Booster(model_file=path).num_trees() == 5


def test_garbage_model_file_raises_typed_error(tmp_path):
    bad = str(tmp_path / "garbage.txt")
    with open(bad, "w") as fh:
        fh.write("this is not a model\nkey=value\n")
    with pytest.raises(ModelCorruptError, match="tree"):
        lgb.Booster(model_file=bad)
    raw = str(tmp_path / "raw.bin")
    with open(raw, "wb") as fh:
        fh.write(bytes(range(256)) * 8)
    with pytest.raises(ModelCorruptError, match="utf-8"):
        lgb.Booster(model_file=raw)


def test_short_field_in_model_rejected(tmp_path):
    X, y = _data()
    bst = lgb.train(PARAMS, lgb.Dataset(X, y), 3)
    lines = bst.model_to_string().splitlines()
    # chop values off a leaf_value line: mid-line truncation that keeps
    # the overall block structure intact must still be caught
    for i, ln in enumerate(lines):
        if ln.startswith("leaf_value=") and len(ln.split()) > 2:
            lines[i] = " ".join(ln.split()[:-1])
            break
    with pytest.raises(ModelCorruptError, match="leaf_value"):
        lgb.Booster(model_str="\n".join(lines))


# -- batcher admission control ----------------------------------------------
def _slow_predict(delay):
    def fn(X, raw):
        time.sleep(delay)
        return np.zeros(X.shape[0], np.float32)
    return fn


def test_batcher_close_fails_queued_requests_promptly():
    mb = MicroBatcher(_slow_predict(1.0), max_batch_rows=1, name="t_close")
    first = mb.submit(np.zeros((1, 3)))
    time.sleep(0.1)  # worker now busy with `first`
    queued = mb.submit(np.zeros((1, 3)))
    t0 = time.monotonic()
    mb.close(timeout=0.1)
    assert time.monotonic() - t0 < 0.8  # no waiting out the device call
    with pytest.raises(ServerClosed):
        queued.result(timeout=1.0)
    with pytest.raises(ServerClosed):
        mb.submit(np.zeros((1, 3)))
    first.result(timeout=5.0)  # in-flight work still completes


def test_batcher_queue_full_sheds():
    mb = MicroBatcher(_slow_predict(0.4), max_batch_rows=4,
                      max_queue_rows=8, name="t_shed")
    try:
        futs = [mb.submit(np.zeros((1, 3)))]
        time.sleep(0.1)  # worker picked up the first request
        futs += [mb.submit(np.zeros((4, 3))), mb.submit(np.zeros((4, 3)))]
        with pytest.raises(QueueFullError) as exc_info:
            mb.submit(np.zeros((1, 3)))
        assert exc_info.value.retry_after > 0
        assert exc_info.value.limit_rows == 8
        for f in futs:  # shed protected the admitted work
            assert f.result(timeout=10.0) is not None
    finally:
        mb.close()


def test_batcher_deadline_expires_queued_work():
    mb = MicroBatcher(_slow_predict(0.5), max_batch_rows=1, name="t_dl")
    try:
        mb.submit(np.zeros((1, 3)))  # occupies the worker
        time.sleep(0.05)
        with pytest.raises(DeadlineExceeded):
            mb.predict(np.zeros((1, 3)), timeout_s=0.1)
    finally:
        mb.close()


def test_batcher_worker_survives_error_on_expired_future():
    """A predict_fn failure racing a client-side deadline expiry must not
    kill the worker thread: the error-path set_exception hits an
    already-failed future and has to swallow InvalidStateError."""
    def fail_slowly(X, raw):
        time.sleep(0.4)
        raise RuntimeError("device fell over")

    mb = MicroBatcher(fail_slowly, max_batch_rows=1, name="t_err_race")
    try:
        with pytest.raises(DeadlineExceeded):
            # expires while the worker is inside fail_slowly; the worker's
            # subsequent set_exception lands on a done future
            mb.predict(np.zeros((1, 3)), timeout_s=0.1)
        time.sleep(0.5)  # let the worker hit the race
        # a dead worker would leave this queued forever; a live one fails
        # it promptly with the predict_fn's error
        with pytest.raises(RuntimeError, match="device fell over"):
            mb.predict(np.zeros((1, 3)), timeout_s=5.0)
    finally:
        mb.close()


def test_batcher_no_deadline_unaffected():
    mb = MicroBatcher(_slow_predict(0.0), name="t_ok")
    try:
        out = mb.predict(np.ones((3, 2)), timeout_s=5.0)
        assert out.shape == (3,)
    finally:
        mb.close()


# -- telemetry export --------------------------------------------------------
def test_resilience_metrics_registered(tmp_path):
    from lightgbm_tpu.telemetry.metrics import default_registry
    X, y = _data()
    ck = str(tmp_path / "tele")
    faults.configure("crash_at_iter=2")
    with pytest.raises(InjectedFault):
        lgb.train({**PARAMS, "checkpoint_dir": ck}, lgb.Dataset(X, y), 5)
    faults.clear()
    lgb.train({**PARAMS, "checkpoint_dir": ck, "resume": "latest"},
              lgb.Dataset(X, y), 5)
    snap = default_registry().snapshot()
    assert "checkpoint_write_seconds" in snap
    assert any(s["value"] >= 1 for s in snap["resume_total"]["series"])
    assert any(s["labels"].get("fault") == "crash_at_iter"
               for s in snap["faults_injected_total"]["series"])
