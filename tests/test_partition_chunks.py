"""Regression: the partitioned grower must stay correct when leaf segments
span MULTIPLE sweep chunks.

An earlier version staged rights ascending at (dr - clt): each chunk's
left-garbage landed below the right watermark and silently clobbered the
previous chunks' staged rights — invisible below CHUNK_TAIL (32K) rows, so
the normal-size suite never caught it while every Higgs-scale segment was
partitioned incorrectly.  These tests force tiny chunk constants so
multi-chunk segments occur at test scale, and verify the grown tree is
self-consistent (walking the recorded tree reproduces row_leaf exactly)."""

import collections

import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu.learner.partitioned as part
from lightgbm_tpu.learner.partitioned import make_partitioned_grow_fn
from lightgbm_tpu.ops.split import SplitParams


@pytest.fixture
def small_chunks():
    bulk, tail = part.CHUNK_BULK, part.CHUNK_TAIL
    part.CHUNK_BULK = 8192
    part.CHUNK_TAIL = 4096
    yield
    part.CHUNK_BULK = bulk
    part.CHUNK_TAIL = tail


def _grow_once(N=20000, F=4, B=16, leaves=8, seed=0, bag=None):
    rng = np.random.RandomState(seed)
    X = rng.randint(0, B, (N, F)).astype(np.uint8)
    grad = rng.randn(N).astype(np.float32)
    hess = np.ones(N, np.float32)
    sp = SplitParams(min_data_in_leaf=5)
    grow = make_partitioned_grow_fn(
        num_leaves=leaves, num_features=F, max_bins=B, max_depth=-1,
        split_params=sp, hist_impl="segment")
    mask = jnp.ones(N, jnp.float32) if bag is None else jnp.asarray(bag)
    g = grow(jnp.asarray(X), jnp.asarray(grad), jnp.asarray(hess), mask,
             jnp.full((F,), B, jnp.int32), jnp.zeros((F,), bool),
             jnp.zeros((F,), bool), jnp.zeros((F,), jnp.int32),
             jnp.zeros((F,), jnp.float32), jnp.zeros((2, 2), jnp.uint32),
             (), jnp.ones((F,), bool))
    return X, g


def _walk_all(X, g):
    sf = np.asarray(g.split_feature)
    tb = np.asarray(g.threshold_bin)
    lch = np.asarray(g.left_child)
    rch = np.asarray(g.right_child)

    def walk(row):
        node = 0
        while True:
            nxt = lch[node] if row[sf[node]] <= tb[node] else rch[node]
            if nxt < 0:
                return -nxt - 1
            node = nxt

    return np.array([walk(r) for r in X])


def test_multichunk_partition_matches_tree_walk(small_chunks):
    X, g = _grow_once()
    rl = np.asarray(g.row_leaf)
    np.testing.assert_array_equal(_walk_all(X, g), rl)
    # leaf_count (from histogram sums) must equal the actual partition
    cnt = collections.Counter(rl.tolist())
    lc = np.asarray(g.leaf_count)
    for leaf, c in cnt.items():
        assert abs(lc[leaf] - c) <= 0.5


def test_multichunk_matches_default_chunks():
    """Same tree whether segments are swept in 8K/4K chunks or in one
    default-size chunk (the fix's cross-check: watermark math must not
    depend on the chunk mix)."""
    X, g_small = None, None
    bulk, tail = part.CHUNK_BULK, part.CHUNK_TAIL
    try:
        part.CHUNK_BULK, part.CHUNK_TAIL = 8192, 4096
        X, g_small = _grow_once(leaves=12)
    finally:
        part.CHUNK_BULK, part.CHUNK_TAIL = bulk, tail
    _, g_big = _grow_once(leaves=12)
    np.testing.assert_array_equal(np.asarray(g_small.row_leaf),
                                  np.asarray(g_big.row_leaf))
    np.testing.assert_allclose(np.asarray(g_small.leaf_value),
                               np.asarray(g_big.leaf_value), rtol=2e-4,
                               atol=1e-6)


def test_multichunk_partition_with_bagging(small_chunks):
    rng = np.random.RandomState(3)
    bag = (rng.rand(20000) < 0.7).astype(np.float32)
    X, g = _grow_once(seed=3, bag=bag)
    rl = np.asarray(g.row_leaf)
    np.testing.assert_array_equal(_walk_all(X, g), rl)
    # in-bag counts per leaf match the histogram counts
    lc = np.asarray(g.leaf_count)
    for leaf in range(int(g.num_leaves)):
        assert abs(float(bag[rl == leaf].sum()) - lc[leaf]) <= 0.5
