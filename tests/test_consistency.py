"""CLI <-> Python API consistency suite (reference pattern:
tests/python_package_test/test_consistency.py — run the examples'
train.conf through the CLI and assert the Python API produces the same
model/predictions on the same data)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.cli import main as cli_main
from lightgbm_tpu.config import Config, parse_config_file
from lightgbm_tpu.io_utils import load_data_file, load_sidecar

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def _run_cli_train(example, tmp_path, extra=()):
    conf = os.path.join(EXAMPLES, example, "train.conf")
    model_out = str(tmp_path / "model.txt")
    cwd = os.getcwd()
    os.chdir(os.path.join(EXAMPLES, example))
    try:
        cli_main([f"config={conf}", f"output_model={model_out}",
                  "verbosity=-1", *extra])
    finally:
        os.chdir(cwd)
    return model_out


# CLI<->API parity holds round-by-round, so the examples' full 40-60
# round configs are capped here (training cost is linear in rounds)
ROUNDS = 15


def _python_train(example, num_rounds=None):
    d = os.path.join(EXAMPLES, example)
    params = parse_config_file(os.path.join(d, "train.conf"))
    if num_rounds is not None:  # params' own num_trees wins over the
        params["num_trees"] = num_rounds  # num_boost_round argument
    cfg = Config(params)
    data_path = os.path.join(d, cfg.data)
    X, _, y = load_data_file(data_path, params)
    ds = lgb.Dataset(X, y, params={**params, "verbosity": -1})
    w = load_sidecar(data_path, "weight")
    if w is not None:
        ds.set_weight(w)
    g = load_sidecar(data_path, "query")
    if g is not None:
        ds.set_group(g.astype(np.int64))
    bst = lgb.train({**params, "verbosity": -1}, ds,
                    num_boost_round=num_rounds or cfg.num_iterations)
    return bst, X


@pytest.mark.parametrize("example", ["binary_classification", "regression",
                                     "lambdarank"])
def test_cli_matches_python_api(example, tmp_path):
    model_path = _run_cli_train(example, tmp_path,
                                extra=(f"num_trees={ROUNDS}",))
    cli_bst = lgb.Booster(model_file=model_path)
    py_bst, X = _python_train(example, num_rounds=ROUNDS)
    np.testing.assert_allclose(cli_bst.predict(X), py_bst.predict(X),
                               rtol=1e-6, atol=1e-9)


def test_cli_predict_writes_results(tmp_path):
    model_path = _run_cli_train("regression", tmp_path,
                                extra=(f"num_trees={ROUNDS}",))
    d = os.path.join(EXAMPLES, "regression")
    out = str(tmp_path / "preds.txt")
    cli_main([f"config={os.path.join(d, 'predict.conf')}",
              f"data={os.path.join(d, 'regression.test')}",
              f"input_model={model_path}", f"output_result={out}",
              "verbosity=-1"])
    preds = np.loadtxt(out)
    bst = lgb.Booster(model_file=model_path)
    X, _, _ = load_data_file(os.path.join(d, "regression.test"), {})
    np.testing.assert_allclose(preds, bst.predict(X), rtol=1e-6)


def test_cli_refit(tmp_path):
    model_path = _run_cli_train("regression", tmp_path,
                                extra=(f"num_trees={ROUNDS}",))
    d = os.path.join(EXAMPLES, "regression")
    out_model = str(tmp_path / "refit.txt")
    cli_main(["task=refit",
              f"data={os.path.join(d, 'regression.train')}",
              f"input_model={model_path}", f"output_model={out_model}",
              "refit_decay_rate=0.5", "verbosity=-1"])
    refit_bst = lgb.Booster(model_file=out_model)
    base_bst = lgb.Booster(model_file=model_path)
    assert refit_bst.num_trees() == base_bst.num_trees()


def test_cli_convert_model_compiles_and_matches(tmp_path):
    import ctypes
    import shutil
    model_path = _run_cli_train("regression", tmp_path,
                                extra=(f"num_trees={ROUNDS}",))
    src = str(tmp_path / "model.cpp")
    cli_main(["task=convert_model", f"input_model={model_path}",
              f"convert_model={src}", "verbosity=-1"])
    assert "PredictRaw" in open(src).read()
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++")
    lib = str(tmp_path / "model.so")
    subprocess.check_call([gxx, "-O1", "-shared", "-fPIC", src, "-o", lib])
    cdll = ctypes.CDLL(lib)
    cdll.PredictRaw.restype = ctypes.c_double
    cdll.PredictRaw.argtypes = [ctypes.POINTER(ctypes.c_double)]
    bst = lgb.Booster(model_file=model_path)
    d = os.path.join(EXAMPLES, "regression")
    X, _, _ = load_data_file(os.path.join(d, "regression.test"), {})
    expect = bst.predict(X[:50], raw_score=True)
    got = np.array([cdll.PredictRaw(
        np.ascontiguousarray(row).ctypes.data_as(
            ctypes.POINTER(ctypes.c_double))) for row in X[:50]])
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-7)


def test_cli_subprocess_entrypoint(tmp_path):
    """python -m lightgbm_tpu end-to-end in a real subprocess."""
    d = os.path.join(EXAMPLES, "regression")
    model_out = str(tmp_path / "m.txt")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""))
    subprocess.check_call(
        [sys.executable, "-m", "lightgbm_tpu", "config=train.conf",
         f"output_model={model_out}", "num_trees=5", "verbosity=-1"],
        cwd=d, env=env)
    assert os.path.exists(model_out)
    assert lgb.Booster(model_file=model_out).num_trees() == 5
