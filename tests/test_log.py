"""utils/log tests: callback redirect (the LGBM_RegisterLogCallback
analog), verbosity filtering, and thread-safety of the module-level
sink/verbosity state."""

import threading

import pytest

from lightgbm_tpu.utils import log


@pytest.fixture(autouse=True)
def _restore_log_state():
    old_v = log.get_verbosity()
    yield
    log.set_verbosity(old_v)
    log.register_log_callback(None)


def test_callback_redirect(capsys):
    lines = []
    log.register_log_callback(lines.append)
    log.set_verbosity(log.LEVEL_INFO)
    log.log_info("hello")
    assert lines == ["[LightGBM-TPU] [Info] hello\n"]
    assert capsys.readouterr().out == ""  # redirected, not printed
    # unregistering restores stdout emission
    log.register_log_callback(None)
    log.log_info("back on stdout")
    assert "back on stdout" in capsys.readouterr().out
    assert len(lines) == 1


def test_callback_sees_all_levels(capsys):
    lines = []
    log.register_log_callback(lines.append)
    log.set_verbosity(log.LEVEL_DEBUG)
    log.log_debug("d")
    log.log_info("i")
    log.log_warning("w")
    assert [l.split("] ")[1].rstrip("\n") for l in lines] == \
        ["[Debug", "[Info", "[Warning"]
    assert capsys.readouterr().out == ""


def test_reentrant_callback_does_not_deadlock(capsys):
    """A callback may itself log or swap the sink (the one-shot
    self-unregistering pattern) — the emit lock must be reentrant."""
    seen = []

    def one_shot(msg):
        seen.append(msg)
        log.register_log_callback(None)   # self-unregister under emit
        log.log_info("from inside callback")  # re-entrant emit

    log.set_verbosity(log.LEVEL_INFO)
    log.register_log_callback(one_shot)
    log.log_info("first")
    assert seen == ["[LightGBM-TPU] [Info] first\n"]
    out = capsys.readouterr().out
    assert "from inside callback" in out  # landed on stdout post-swap


def test_verbosity_filtering(capsys):
    log.set_verbosity(log.LEVEL_WARNING)
    log.log_info("hidden info")
    log.log_debug("hidden debug")
    log.log_warning("shown warning")
    out = capsys.readouterr().out
    assert "hidden" not in out and "shown warning" in out
    # below warning: everything but fatal is silent
    log.set_verbosity(log.LEVEL_FATAL)
    log.log_warning("suppressed")
    assert capsys.readouterr().out == ""
    with pytest.raises(log.LightGBMError, match="boom"):
        log.log_fatal("boom")
    # debug verbosity opens the debug channel
    log.set_verbosity(log.LEVEL_DEBUG)
    log.log_debug("now visible")
    assert "[Debug] now visible" in capsys.readouterr().out


def test_thread_safety_of_module_state():
    """Concurrent emitters + concurrent sink/verbosity pokes: every
    message must arrive exactly once, as one intact line, on the
    callback that was registered."""
    lines = []
    log.register_log_callback(lines.append)
    log.set_verbosity(log.LEVEL_INFO)
    n_threads, n_msgs = 8, 200
    barrier = threading.Barrier(n_threads)
    errors = []

    def worker(t):
        try:
            barrier.wait()
            for i in range(n_msgs):
                log.log_info(f"t{t}-m{i}")
                if i % 50 == 25:
                    # racing state pokes must not drop or tear messages
                    log.set_verbosity(log.LEVEL_INFO)
        except Exception as exc:  # pragma: no cover - failure reporting
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(lines) == n_threads * n_msgs
    # intact lines: exactly one prefix and one newline each
    assert all(l.count("[LightGBM-TPU]") == 1 and l.endswith("\n")
               for l in lines)
    # nothing lost per thread
    for t in range(n_threads):
        got = [l for l in lines if f"t{t}-m" in l]
        assert len(got) == n_msgs
