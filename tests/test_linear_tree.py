"""Linear tree tests (reference pattern: test_engine.py linear_tree
cases — piecewise-linear data where linear leaves beat constant leaves;
model IO round trips)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def pw_linear():
    rng = np.random.RandomState(5)
    n = 1500
    x0 = rng.rand(n) * 4
    x1 = rng.randn(n)
    # piecewise-LINEAR target: constant leaves need depth to approximate,
    # linear leaves nail it with few splits
    y = np.where(x0 < 2, 3 * x0 + 1, -2 * x0 + 11) + 0.5 * x1 \
        + 0.05 * rng.randn(n)
    return np.stack([x0, x1], 1), y


PARAMS = {"objective": "regression", "num_leaves": 8, "verbosity": -1,
          "metric": "l2", "learning_rate": 0.2}


def test_linear_beats_constant(pw_linear):
    X, y = pw_linear
    plain = lgb.train(PARAMS, lgb.Dataset(X, y), 20)
    linear = lgb.train({**PARAMS, "linear_tree": True}, lgb.Dataset(X, y), 20)
    mse_p = np.mean((plain.predict(X) - y) ** 2)
    mse_l = np.mean((linear.predict(X) - y) ** 2)
    assert mse_l < mse_p * 0.5
    trees = linear._gbdt.models
    assert trees[0].is_linear
    assert any(len(f) > 0 for t in trees for f in t.leaf_features)


def test_linear_model_roundtrip(pw_linear, tmp_path):
    X, y = pw_linear
    bst = lgb.train({**PARAMS, "linear_tree": True}, lgb.Dataset(X, y), 10)
    p0 = bst.predict(X)
    path = str(tmp_path / "linear.txt")
    bst.save_model(path)
    assert "is_linear=1" in open(path).read()
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(loaded.predict(X), p0, rtol=1e-5, atol=1e-6)


def test_linear_nan_fallback(pw_linear):
    X, y = pw_linear
    bst = lgb.train({**PARAMS, "linear_tree": True}, lgb.Dataset(X, y), 10)
    Xn = X[:20].copy()
    Xn[:, 1] = np.nan  # x1 appears in leaf models -> fallback must engage
    pred = bst.predict(Xn)
    assert np.all(np.isfinite(pred))


def test_linear_train_score_consistency(pw_linear):
    """Training-time scores (device path) must equal predict() (batch
    walk): catches divergence between the two linear evaluators."""
    X, y = pw_linear
    bst = lgb.train({**PARAMS, "linear_tree": True}, lgb.Dataset(X, y), 8)
    train_score = np.asarray(bst._gbdt.score)
    np.testing.assert_allclose(train_score, bst.predict(X), rtol=1e-4,
                               atol=1e-5)


def test_linear_with_valid_and_early_stop(pw_linear):
    X, y = pw_linear
    evals = {}
    ds = lgb.Dataset(X[:1000], y[:1000])
    bst = lgb.train({**PARAMS, "linear_tree": True}, ds, 30,
                    valid_sets=[ds.create_valid(X[1000:], y[1000:])],
                    callbacks=[lgb.record_evaluation(evals)])
    l2 = evals["valid_0"]["l2"]
    assert l2[-1] < l2[0] * 0.2
    # valid-score bookkeeping matches a fresh predict
    np.testing.assert_allclose(np.asarray(bst._gbdt.valid_scores[0]),
                               bst.predict(X[1000:]), rtol=1e-4, atol=1e-5)


def test_linear_host_predict_agrees(pw_linear):
    X, y = pw_linear
    bst = lgb.train({**PARAMS, "linear_tree": True}, lgb.Dataset(X, y), 5)
    gbdt = bst._gbdt
    host = sum(t.predict(X[:100][:, gbdt.train_set.used_feature_map])
               for t in gbdt.models)
    np.testing.assert_allclose(host, bst.predict(X[:100], raw_score=True),
                               rtol=1e-5, atol=1e-6)


def test_linear_refit():
    """Refit of a linear-tree model: structures + coefficients keep, leaf
    values/constants re-center on the new data with the decay mix."""
    rng = np.random.RandomState(9)
    X = rng.randn(800, 5).astype(np.float64)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.randn(800)
    p = {"objective": "regression", "num_leaves": 7, "linear_tree": True,
         "verbosity": -1, "min_data_in_leaf": 10}
    bst = lgb.train(p, lgb.Dataset(X, y), 8)
    X2 = rng.randn(600, 5).astype(np.float64)
    y2 = X2[:, 0] * 2 + X2[:, 1] + 0.5 + 0.1 * rng.randn(600)  # shifted
    re = bst.refit(X2, y2, decay_rate=0.5)
    assert re.num_trees() == bst.num_trees()
    t0, r0 = bst._gbdt.models[0], re._gbdt.models[0]
    assert r0.is_linear and t0.num_leaves == r0.num_leaves
    np.testing.assert_array_equal(t0.split_feature, r0.split_feature)
    # coefficients preserved; constants shifted by the refit delta
    for a, b in zip(t0.leaf_coeff, r0.leaf_coeff):
        np.testing.assert_allclose(a, b, rtol=1e-7)
    pr = re.predict(X2)
    assert np.all(np.isfinite(pr))
    # refit toward the shifted data beats the unrefit model there
    assert np.mean((pr - y2) ** 2) < np.mean((bst.predict(X2) - y2) ** 2)
