"""Model serialization tests (reference gbdt_model_text.cpp format;
analog of parts of test_engine.py save/load and test_basic.py)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


def test_roundtrip_exact(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary"}, lgb.Dataset(X, y), 8)
    p = bst.predict(X)
    s = bst.model_to_string()
    bst2 = lgb.Booster(model_str=s)
    np.testing.assert_allclose(bst2.predict(X), p, rtol=1e-6)
    # and via file
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.txt")
        bst.save_model(path)
        bst3 = lgb.Booster(model_file=path)
        np.testing.assert_allclose(bst3.predict(X), p, rtol=1e-6)


def test_model_format_headers(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary"}, lgb.Dataset(X, y), 3)
    s = bst.model_to_string()
    lines = s.splitlines()
    assert lines[0] == "tree"
    assert lines[1] == "version=v3"
    assert any(l.startswith("objective=binary") for l in lines)
    assert any(l.startswith("feature_names=") for l in lines)
    assert any(l.startswith("tree_sizes=") for l in lines)
    assert any(l.startswith("Tree=0") for l in lines)
    assert "end of trees" in s
    assert "feature_importances:" in s
    # per-tree blocks carry the reference keys
    for key in ("num_leaves=", "split_feature=", "threshold=",
                "decision_type=", "left_child=", "right_child=",
                "leaf_value=", "internal_count=", "shrinkage="):
        assert key in s


def test_multiclass_roundtrip(multiclass_data):
    X, y = multiclass_data
    bst = lgb.train({**SMALL, "objective": "multiclass", "num_class": 3},
                    lgb.Dataset(X, y), 5)
    p = bst.predict(X)
    bst2 = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(bst2.predict(X), p, rtol=1e-5)


def test_dump_model(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary"}, lgb.Dataset(X, y), 3)
    d = bst.dump_model()
    assert d["version"] == "v3"
    assert d["num_class"] == 1
    assert len(d["tree_info"]) == 3
    t0 = d["tree_info"][0]["tree_structure"]
    assert "split_feature" in t0
    assert "left_child" in t0
    # leaf count reachable from structure equals num_leaves
    def count_leaves(node):
        if "split_feature" not in node:
            return 1
        return count_leaves(node["left_child"]) + count_leaves(node["right_child"])
    assert count_leaves(t0) == d["tree_info"][0]["num_leaves"]


def test_pred_leaf(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary"}, lgb.Dataset(X, y), 4)
    leaves = bst.predict(X[:50], pred_leaf=True)
    assert leaves.shape == (50, 4)
    assert (leaves >= 0).all()
    assert (leaves < 7).all()


def test_pred_contrib(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary"}, lgb.Dataset(X, y), 3)
    contrib = bst.predict(X[:20], pred_contrib=True)
    assert contrib.shape == (20, X.shape[1] + 1)
    raw = bst.predict(X[:20], raw_score=True)
    # SHAP sums to the raw prediction
    np.testing.assert_allclose(contrib.sum(axis=1), raw, rtol=1e-4, atol=1e-4)


def test_feature_importance(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary"}, lgb.Dataset(X, y), 5)
    imp_split = bst.feature_importance("split")
    imp_gain = bst.feature_importance("gain")
    assert imp_split.shape == (X.shape[1],)
    assert imp_split.sum() > 0
    assert imp_gain.sum() > 0
    # the truly predictive feature (0) should matter
    assert imp_split[0] > 0


def test_save_binary_dataset(tmp_path, binary_data):
    X, y = binary_data
    ds = lgb.Dataset(X, y)
    ds.construct()
    path = str(tmp_path / "data.bin")
    ds.save_binary(path)
    ds2 = lgb.Dataset.load_binary(path)
    assert ds2.num_data() == ds.num_data()
    assert ds2.num_feature() == ds.num_feature()
    np.testing.assert_array_equal(ds2.X_binned, ds.X_binned)
    np.testing.assert_array_equal(ds2.get_label(), ds.get_label())
    # trainable
    bst = lgb.train({**SMALL, "objective": "binary"}, ds2, 3)
    assert bst.num_trees() == 3


def test_save_binary_is_atomic_and_validated(tmp_path, binary_data):
    """save_binary writes through atomic_write_bytes (no partial file on
    crash) and load_binary rejects truncated/garbage payloads with a
    typed DatasetCorruptError validated against fingerprint() fields."""
    import os

    from lightgbm_tpu.dataset import DatasetCorruptError
    X, y = binary_data
    ds = lgb.Dataset(X, y)
    ds.construct()
    path = str(tmp_path / "data.bin")
    ds.save_binary(path)
    # no temp litter from the atomic write
    assert [f for f in os.listdir(tmp_path) if f.startswith(".")] == []

    # truncated payload -> typed error, not a raw pickle exception
    raw = open(path, "rb").read()
    with open(str(tmp_path / "trunc.bin"), "wb") as fh:
        fh.write(raw[:len(raw) // 2])
    with pytest.raises(DatasetCorruptError):
        lgb.Dataset.load_binary(str(tmp_path / "trunc.bin"))

    # garbage bytes -> typed error
    with open(str(tmp_path / "junk.bin"), "wb") as fh:
        fh.write(b"not a dataset at all")
    with pytest.raises(DatasetCorruptError):
        lgb.Dataset.load_binary(str(tmp_path / "junk.bin"))

    # a wrong-format pickle -> typed error naming the format marker
    import pickle
    with open(str(tmp_path / "fmt.bin"), "wb") as fh:
        pickle.dump({"format": "something.else"}, fh)
    with pytest.raises(DatasetCorruptError, match="format"):
        lgb.Dataset.load_binary(str(tmp_path / "fmt.bin"))

    # a missing required field -> typed error naming it
    import pickle as _p
    payload = _p.loads(raw)
    del payload["bin_mappers"]
    with open(str(tmp_path / "miss.bin"), "wb") as fh:
        _p.dump(payload, fh)
    with pytest.raises(DatasetCorruptError, match="bin_mappers"):
        lgb.Dataset.load_binary(str(tmp_path / "miss.bin"))

    # binned codes flipped after save -> fingerprint mismatch
    payload = _p.loads(raw)
    Xb = np.array(payload["X_binned"], copy=True)
    Xb[0, 0] = (Xb[0, 0] + 1) % 4
    payload["X_binned"] = Xb
    with open(str(tmp_path / "flip.bin"), "wb") as fh:
        _p.dump(payload, fh)
    with pytest.raises(DatasetCorruptError, match="fingerprint"):
        lgb.Dataset.load_binary(str(tmp_path / "flip.bin"))

    # DatasetCorruptError is a ValueError (back-compat with callers
    # catching the old raw ValueError)
    assert issubclass(DatasetCorruptError, ValueError)


def test_num_iteration_predict(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary"}, lgb.Dataset(X, y), 10)
    p5 = bst.predict(X, num_iteration=5, raw_score=True)
    p10 = bst.predict(X, raw_score=True)
    assert not np.allclose(p5, p10)


def test_binary_cache_valid_set_accepted(tmp_path, binary_data):
    """A valid set built against the train reference, saved to the binary
    cache and reloaded, has equal-but-not-identical bin mappers — the
    value-based alignment check (dataset.h:304 CheckAlign analog) must
    accept it."""
    X, y = binary_data
    ds = lgb.Dataset(X, y)
    ds.construct()
    vs = lgb.Dataset(X[:200], y[:200], reference=ds)
    vs.construct()
    path = str(tmp_path / "valid.bin")
    vs.save_binary(path)
    vs2 = lgb.Dataset.load_binary(path)
    bst = lgb.train({**SMALL, "objective": "binary"}, ds, 3,
                    valid_sets=[vs2])
    assert bst.num_trees() == 3
