"""Trace-lint subsystem (ISSUE 10 tentpole, lightgbm_tpu/analysis/).

Contract under test:
  * the shared jaxpr walker descends through pjit/while/cond/scan/
    shard_map sub-jaxprs (the API the three former test-local walkers
    migrated onto — assertions there unchanged);
  * each of the six rules FIRES on a planted violation with an
    actionable, site-named diagnostic, and stays quiet on clean
    programs;
  * `run_lint` passes on matrix configs at head and the CLI exits
    nonzero when any contract is violated.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from lightgbm_tpu.analysis import contracts, ir, lint
from lightgbm_tpu.analysis.rules import (CollectiveBudgetRule,
                                         ConstantFoldRule, DonationRule,
                                         DtypeRule, HostSyncRule,
                                         RetraceRule, TraceUnit)
from lightgbm_tpu.telemetry import _config as tele_config
from lightgbm_tpu.telemetry.train_record import note_collective


# ---------------------------------------------------------------------------
# ir: the shared walker
# ---------------------------------------------------------------------------

def _nested_program(x):
    def body(c, _):
        return c + 1.0, c

    def cond_true(v):
        return v * 2.0

    def cond_false(v):
        return v - 1.0

    c, ys = jax.lax.scan(body, x, None, length=3)
    c = jax.lax.cond(c[0] > 0, cond_true, cond_false, c)
    return jax.jit(lambda a: a + ys.sum(0))(c)


def test_ir_walks_nested_subjaxprs():
    jx = ir.trace(_nested_program, jnp.ones((4,)))
    prims = [info.prim for info in ir.iter_eqns(jx)]
    assert "scan" in prims and "cond" in prims and "pjit" in prims
    # eqns INSIDE the scan body were visited and carry the loop path
    in_scan = [info for info in ir.iter_eqns(jx) if "scan" in info.path]
    assert in_scan and all(info.in_loop for info in in_scan)
    # the tuple API mirrors the old test-local walker
    names = [n for n, _ in ir.walk_eqns(jx)]
    assert names == prims
    assert ir.count_primitive(jx, "cond") == 1


def test_ir_descends_pallas_call_kernels():
    """The walker enumerates eqns INSIDE pallas_call kernel jaxprs
    (claimed since PR 10, pinned here): both on a synthetic kernel and
    on the real wave grower's traced program."""
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0 + 1.0

    def f(x):
        return pl.pallas_call(
            kernel, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=True)(x)

    jx = ir.trace(f, jnp.ones((8, 128), jnp.float32))
    inside = [info for info in ir.iter_eqns(jx)
              if "pallas_call" in info.path]
    assert inside, "no eqns enumerated inside the pallas kernel jaxpr"
    prims = {info.prim for info in inside}
    assert "mul" in prims and "add" in prims
    # the real thing: the wave config's program carries pallas kernels
    # and the walker sees their interiors too
    unit = lint.build_unit("wave")
    in_kernel = [info for info in ir.iter_eqns(unit.jaxpr)
                 if "pallas_call" in info.path]
    assert in_kernel, "wave program pallas kernels not descended"


def test_ir_descends_custom_jvp_and_vjp_bodies():
    @jax.custom_jvp
    def f(x):
        return jnp.sin(x) * x

    @f.defjvp
    def f_jvp(primals, tangents):
        (x,), (t,) = primals, tangents
        return f(x), (jnp.cos(x) * x + jnp.sin(x)) * t

    jx = ir.trace(lambda x: f(x) + 1.0, jnp.ones((4,)))
    in_jvp = [info for info in ir.iter_eqns(jx)
              if any(p.startswith("custom_jvp_call") for p in info.path)]
    assert in_jvp and "sin" in {i.prim for i in in_jvp}

    @jax.custom_vjp
    def g(x):
        return jnp.tanh(x) * 3.0

    def g_fwd(x):
        return g(x), x

    def g_bwd(res, ct):
        return (ct * (1 - jnp.tanh(res) ** 2) * 3.0,)

    g.defvjp(g_fwd, g_bwd)
    jxg = ir.trace(lambda x: g(x) * 2.0, jnp.ones((4,)))
    in_vjp = [info for info in ir.iter_eqns(jxg)
              if any(p.startswith("custom_vjp_call") for p in info.path)]
    assert in_vjp and "tanh" in {i.prim for i in in_vjp}


def test_ir_stable_hash_and_consts():
    jx1 = ir.trace(_nested_program, jnp.ones((4,)))
    jx2 = ir.trace(_nested_program, jnp.ones((4,)))
    assert ir.stable_hash(jx1) == ir.stable_hash(jx2)
    assert ir.stable_hash(jx1) != ir.stable_hash(
        ir.trace(_nested_program, jnp.ones((8,))))
    big = jnp.zeros((64, 64))
    jc = ir.trace(lambda x: x + big.sum(), jnp.ones(()))
    shapes = [tuple(getattr(c, "shape", ())) for c, _ in ir.iter_consts(jc)]
    assert (64, 64) in shapes


# ---------------------------------------------------------------------------
# collective-budget rule: planted full-histogram psum / undeclared site /
# tally-vs-program drift
# ---------------------------------------------------------------------------

def _mesh8():
    from lightgbm_tpu.parallel.mesh import get_mesh
    return get_mesh(8)


def _shard_psum(fn_site, payload_shape):
    """shard_map program psum-ing one payload, tallied at ``fn_site``."""
    from jax.sharding import PartitionSpec as P
    from lightgbm_tpu.parallel.mesh import shard_map_compat
    mesh = _mesh8()
    ax = mesh.axis_names[0]

    def f(x):
        note_collective(fn_site, "psum", x)
        return jax.lax.psum(x, ax)

    return shard_map_compat(f, mesh=mesh, in_specs=(P(ax),),
                            out_specs=P(ax)), \
        jnp.ones((8,) + payload_shape, jnp.float32)


def _unit_for(fn, args, site_filter=None, **ctx):
    from lightgbm_tpu.telemetry.train_record import collectives_snapshot
    before = collectives_snapshot()
    jx = ir.trace(lambda *a: fn(*a), *args)
    after = collectives_snapshot()
    delta = {}
    for site, rec in after.items():
        base = before.get(site, {"count": 0, "bytes": 0})
        dc = rec["count"] - base["count"]
        if dc > 0 and (site_filter is None or site.startswith(site_filter)):
            delta[site] = {"op": rec["op"], "count": dc,
                           "bytes": rec["bytes"] - base["bytes"]}
    return TraceUnit(name="planted", jaxpr=jx, ctx=ctx, collectives=delta)


@pytest.mark.skipif(not tele_config.enabled(),
                    reason="telemetry disabled via LGBM_TPU_TELEMETRY=0")
def test_budget_rule_flags_full_histogram_psum():
    """A psum moving more bytes than the site's declared per-op budget
    — the full-histogram-leak class — fires with the site name."""
    site = "test/hist_merge"
    contracts.collective_contract(site, "psum", max_count=4,
                                  max_bytes_per_op=1024)
    try:
        fn, x = _shard_psum(site, (64, 64, 3))  # 48 KB >> 1 KB budget
        unit = _unit_for(fn, (x,), site_filter="test/")
        vs = CollectiveBudgetRule().check(unit)
        assert any(site in v.message and "bytes/op" in v.message
                   for v in vs), vs
    finally:
        contracts.remove_collective_contract(site)


@pytest.mark.skipif(not tele_config.enabled(),
                    reason="telemetry disabled via LGBM_TPU_TELEMETRY=0")
def test_budget_rule_flags_count_overrun_and_undeclared_site():
    site = "test/one_merge_only"
    contracts.collective_contract(site, "psum", max_count=1)
    try:
        from jax.sharding import PartitionSpec as P
        from lightgbm_tpu.parallel.mesh import shard_map_compat
        mesh = _mesh8()
        ax = mesh.axis_names[0]

        def f(x):
            note_collective(site, "psum", x)
            a = jax.lax.psum(x, ax)
            note_collective(site, "psum", x)
            b = jax.lax.psum(x * 2, ax)
            note_collective("test/undeclared_site", "pmax", x)
            c = jax.lax.pmax(x, ax)
            return a + b + c

        fn = shard_map_compat(f, mesh=mesh, in_specs=(P(ax),),
                              out_specs=P(ax))
        unit = _unit_for(fn, (jnp.ones((16,)),), site_filter="test/")
        vs = CollectiveBudgetRule().check(unit)
        msgs = "\n".join(v.message for v in vs)
        assert "traced 2 collective(s)" in msgs and site in msgs
        assert "no declared contract" in msgs and \
            "test/undeclared_site" in msgs
    finally:
        contracts.remove_collective_contract(site)


@pytest.mark.skipif(not tele_config.enabled(),
                    reason="telemetry disabled via LGBM_TPU_TELEMETRY=0")
def test_budget_rule_flags_untallied_collective_drift():
    """A collective op in the program with NO note_collective tally:
    the contract/tally drift class."""
    from jax.sharding import PartitionSpec as P
    from lightgbm_tpu.parallel.mesh import shard_map_compat
    mesh = _mesh8()
    ax = mesh.axis_names[0]
    fn = shard_map_compat(lambda x: jax.lax.psum(x, ax), mesh=mesh,
                          in_specs=(P(ax),), out_specs=P(ax))
    unit = _unit_for(fn, (jnp.ones((16,)),), site_filter="test/")
    vs = CollectiveBudgetRule().check(unit)
    assert any("drifted" in v.message and v.site == "<program>"
               for v in vs), vs


# ---------------------------------------------------------------------------
# host-sync rule: planted callback in a hot loop
# ---------------------------------------------------------------------------

def test_host_sync_rule_flags_callback_in_loop():
    def body(c, _):
        pulled = jax.pure_callback(
            lambda a: np.asarray(a), jax.ShapeDtypeStruct((), jnp.float32),
            c)
        return c + pulled, None

    def f(x):
        out, _ = jax.lax.scan(body, x, None, length=4)
        return out

    unit = TraceUnit(name="planted", jaxpr=ir.trace(f, jnp.float32(1.0)))
    vs = HostSyncRule().check(unit)
    assert vs and "pure_callback" in vs[0].message
    assert "hot loop" in vs[0].message and "scan" in vs[0].message
    # a clean program stays quiet
    clean = TraceUnit(name="ok", jaxpr=ir.trace(
        lambda x: x * 2, jnp.ones((4,))))
    assert HostSyncRule().check(clean) == []


# ---------------------------------------------------------------------------
# dtype rule: planted f64 on device
# ---------------------------------------------------------------------------

def test_dtype_rule_flags_f64():
    jax.config.update("jax_enable_x64", True)
    try:
        jx = ir.trace(lambda x: x * 2.0 + 1.0,
                      np.ones((8,), np.float64))
        unit = TraceUnit(name="planted", jaxpr=jx)
        vs = DtypeRule().check(unit)
        assert vs and "float64" in vs[0].message
        # an x64-sanctioned config allowlists it
        ok = TraceUnit(name="x64ok", jaxpr=jx, ctx={"allow_f64": True})
        assert DtypeRule().check(ok) == []
    finally:
        jax.config.update("jax_enable_x64", False)


def test_dtype_rule_forbid_extra_dtypes():
    """Quantized paths can forbid f32 histogram payloads outright."""
    jx = ir.trace(lambda x: x.astype(jnp.float16) * 2,
                  jnp.ones((8,), jnp.float32))
    unit = TraceUnit(name="planted", jaxpr=jx,
                     ctx={"forbid_dtypes": ("float16",)})
    vs = DtypeRule().check(unit)
    assert vs and "float16" in vs[0].message


# ---------------------------------------------------------------------------
# constant-fold rule: planted giant constant
# ---------------------------------------------------------------------------

def test_constant_fold_rule_flags_giant_constant():
    giant = jnp.zeros((512, 257), jnp.float32)  # 131584 elems > 2**16

    def f(x):
        # the constant must meet a TRACER to enter the jaxpr (a fully
        # concrete subexpression folds at trace time already)
        return jnp.sum(x + giant)

    unit = TraceUnit(name="planted", jaxpr=ir.trace(f, jnp.float32(0.0)))
    vs = ConstantFoldRule().check(unit)
    assert vs, "giant closed-over constant not flagged"
    assert "(512, 257)" in vs[0].message and "argument" in vs[0].message
    # small constants stay quiet ...
    cst = jnp.ones((64,), jnp.float32)
    small = TraceUnit(name="ok", jaxpr=ir.trace(
        lambda x: jnp.sum(x + cst), jnp.float32(0.0)))
    assert ConstantFoldRule().check(small) == []
    # ... and the threshold is ctx-tunable in both directions
    tight = TraceUnit(name="tight", jaxpr=small.jaxpr,
                      ctx={"const_fold_max_elems": 16})
    assert ConstantFoldRule().check(tight)
    loose = TraceUnit(name="loose", jaxpr=unit.jaxpr,
                      ctx={"const_fold_max_elems": 1 << 20})
    assert ConstantFoldRule().check(loose) == []


# ---------------------------------------------------------------------------
# retrace rule: planted hash flip across same-shape traces
# ---------------------------------------------------------------------------

def test_retrace_rule_flags_unstable_program():
    # two same-shape traces of one label landing on different programs
    # (the trace-dependent-Python-value class)
    h0 = ir.stable_hash(ir.trace(lambda x: x * 2, jnp.ones((4,))))
    h1 = ir.stable_hash(ir.trace(lambda x: x + 1, jnp.ones((4,))))
    assert h0 != h1
    unit = TraceUnit(name="planted",
                     hashes=[("iteration", h0), ("iteration", h1)])
    vs = RetraceRule().check(unit)
    assert vs and "iteration" in vs[0].site and "recompiles" in vs[0].message
    stable = TraceUnit(name="ok", hashes=[("it", "aaaa"), ("it", "aaaa")])
    assert RetraceRule().check(stable) == []


def test_retrace_rule_bounds_program_ladder():
    unit = TraceUnit(name="serve",
                     hashes=[("b1", "h1"), ("b8", "h2"), ("b64", "h3")],
                     ctx={"max_distinct_programs": 2})
    vs = RetraceRule().check(unit)
    assert vs and "3 distinct compiled programs" in vs[0].message


# ---------------------------------------------------------------------------
# donation rule: planted un-aliasable donation + the real score update
# ---------------------------------------------------------------------------

def test_donation_rule_flags_unaliasable_buffer():
    def bad_update(score, delta):
        return (score + delta).astype(jnp.bfloat16)  # dtype drift!

    c = contracts.DonationContract(
        name="test/bad_score_update",
        fn_ref=lambda: jax.jit(bad_update, donate_argnums=(0,)),
        donate_argnums=(0,),
        build_args=lambda: (jnp.zeros((32,), jnp.float32),
                            jnp.zeros((32,), jnp.float32)),
        declared_in="tests.test_analysis")
    vs = DonationRule().check_contract(c, TraceUnit(name="donation"))
    assert vs and "cannot alias" in vs[0].message and \
        "test/bad_score_update" in vs[0].message


def test_donation_rule_passes_real_score_update():
    from lightgbm_tpu.models import gbdt  # noqa: F401  (registers the contract)
    cs = contracts.all_donation_contracts()
    assert "gbdt/score_update" in cs
    vs = DonationRule().check_contract(cs["gbdt/score_update"],
                                       TraceUnit(name="donation"))
    assert vs == [], vs


def test_donated_score_update_bit_identical():
    """The donated and undonated score-update entries produce the same
    bits (donation only changes buffer reuse, never math)."""
    from lightgbm_tpu.models.gbdt import (_update_score_by_leaf,
                                          _update_score_by_leaf_donated)
    rng = np.random.RandomState(0)
    score = jnp.asarray(rng.randn(257).astype(np.float32))
    rl = jnp.asarray(rng.randint(0, 7, 257).astype(np.int32))
    lv = jnp.asarray(rng.randn(7).astype(np.float32))
    want = np.asarray(_update_score_by_leaf(score, rl, lv, 1.0))
    # donate a fresh, settled copy: the XLA:CPU runtime frees donated
    # buffers under in-flight readers (the reason gbdt gates the donated
    # dispatch TPU-only), so the shared `score` must not be the donated
    # operand and nothing may be pending when the donation dispatches
    score_d = jax.block_until_ready(jnp.array(score, copy=True))
    got = np.asarray(_update_score_by_leaf_donated(score_d, rl, lv, 1.0))
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# the lint driver + CLI
# ---------------------------------------------------------------------------

def test_run_lint_serial_and_serve_clean():
    report = lint.run_lint(["serial", "serve"])
    assert report["schema"] == "trace-lint-v1"
    assert report["ok"], report
    assert report["configs"]["serial"]["ok"]
    assert report["configs"]["serve"]["ok"]
    # the serve ladder is hash-stable: 5 buckets -> 5 programs max
    assert report["configs"]["score_update"]["ok"]


@pytest.mark.skipif(not tele_config.enabled(),
                    reason="telemetry disabled via LGBM_TPU_TELEMETRY=0")
def test_run_lint_dp_scatter_contracts_hold():
    """The tentpole acceptance config: one reduce_scatter per merge
    site, O(W*k) exchange, everything tallied and under contract."""
    report = lint.run_lint(["dp_scatter"])
    assert report["ok"], report["configs"]["dp_scatter"]["violations"]
    coll = report["configs"]["dp_scatter"]["collectives"]
    rs = coll.get("data_parallel/wave/hist_reduce_scatter")
    if rs is not None:  # 8 virtual devices available (conftest forces it)
        assert rs["count"] == 3  # root + wave body + endgame bank
        assert "data_parallel/wave/winner_exchange" in coll


def test_lint_cli_exit_codes(tmp_path, capsys):
    out = tmp_path / "report.json"
    rc = lint.main(["configs=serve", f"out={out}"])
    assert rc == 0 and out.exists()
    import json
    rep = json.loads(out.read_text())
    assert rep["schema"] == "trace-lint-v1" and rep["ok"]
    capsys.readouterr()

    # plant a broken donation contract -> the SAME CLI must exit nonzero
    # with a site-named diagnostic in the report
    contracts.donation_contract(
        "test/planted_bad_donation",
        lambda: jax.jit(lambda s, d: (s + d).astype(jnp.int32),
                        donate_argnums=(0,)),
        (0,),
        lambda: (jnp.zeros((16,), jnp.float32),
                 jnp.zeros((16,), jnp.float32)))
    try:
        rc = lint.main(["configs=serve", f"out={out}"])
        assert rc != 0
        rep = json.loads(out.read_text())
        assert not rep["ok"]
        msgs = json.dumps(rep["configs"]["score_update"]["violations"])
        assert "test/planted_bad_donation" in msgs
    finally:
        contracts.remove_donation_contract("test/planted_bad_donation")
    capsys.readouterr()


def test_contract_registry_covers_all_note_collective_sites():
    """Every note_collective site in the source tree has a declared
    contract — grep the tree so a new collective cannot land without
    one (the drift guard's static half)."""
    import re
    from pathlib import Path

    # contracts register at module import; pull in every declaring module
    # so this test is order-independent (it must pass in isolation too)
    import lightgbm_tpu.learner.wave  # noqa: F401
    import lightgbm_tpu.parallel.data_parallel  # noqa: F401
    import lightgbm_tpu.parallel.feature_parallel  # noqa: F401
    import lightgbm_tpu.parallel.voting_parallel  # noqa: F401
    root = Path(__file__).resolve().parent.parent / "lightgbm_tpu"
    pat = re.compile(r"note_collective\(\s*[\"']([^\"']+)[\"']")
    sites = set()
    for path in root.rglob("*.py"):
        sites.update(pat.findall(path.read_text()))
    assert sites, "note_collective sites vanished?"
    declared = set(contracts.all_contracts())
    missing = sites - declared
    assert not missing, (
        f"collective sites without a declared contract: {sorted(missing)} "
        f"— add analysis.contracts.collective_contract next to each")
