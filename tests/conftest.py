"""Test configuration: force an 8-device CPU mesh (SURVEY.md §4's
"multi-host simulated by multi-process/mesh-sharding on a single host" —
the reference's analog is test_dask.py's in-process multi-worker cluster).

Must run before any jax client is created.  The container's sitecustomize
registers the axon TPU backend eagerly, so we switch platforms via
jax.config (which wins over the registered plugin) and raise the CPU device
count for shard_map tests."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax has no jax_num_cpu_devices; the XLA_FLAGS knob is the
    # portable spelling and is read at first backend creation (setting
    # BOTH on newer jax is rejected, so only set it on the fallback)
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=8").strip()
# Persistent compile cache: ~190 tests trigger hundreds of XLA:CPU
# compilations in one process; caching them on disk cuts repeat-run time
# drastically and reduces exposure to rare in-process compiler crashes
# observed after long compile sequences.
_cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
# ... and export the same cache to every subprocess tests spawn (gloo
# worker pairs, CLI entrypoint runs, fleet serve workers): each of those
# is a fresh jax that would otherwise recompile its whole program set
# per run.  jax reads these env spellings at import.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache_dir)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.0")

import numpy as np
import pytest

# tree_learner=feature requires jax.shard_map (jax>=0.5).  On this env's
# jax 0.4.37 the legacy SPMD partitioner hard-aborts the PROCESS (CHECK
# failure in hlo_sharding_util merging manual/tuple shardings) compiling
# the feature-parallel shard_map program, so
# FeatureParallelTreeLearner.__init__ raises cleanly instead of training
# (lightgbm_tpu/parallel/feature_parallel.py:110-116).  Tests that train
# with tree_learner=feature carry this skip; they run again the moment
# the env's jax grows jax.shard_map.
FEATURE_PARALLEL_OK = hasattr(jax, "shard_map")
FP_SKIP = pytest.mark.skipif(
    not FEATURE_PARALLEL_OK,
    reason="tree_learner=feature needs jax.shard_map (jax>=0.5); this "
           "jax's legacy SPMD partitioner aborts compiling the FP "
           "program — see tests/conftest.py and "
           "lightgbm_tpu/parallel/feature_parallel.py:110")


@pytest.fixture(autouse=True, scope="module")
def _bound_jit_cache():
    """Release compiled executables after each test module.

    XLA:CPU maps every live compiled executable into the process; across
    ~190 tests the mapping count reaches vm.max_map_count (65530 default)
    and the NEXT compile segfaults (reproduced deterministically; maps
    measured at 64.5K right before SIGSEGV).  Clearing jit caches per
    module unmaps retired executables; the persistent compile cache below
    makes any re-compile a cheap disk deserialize."""
    yield
    jax.clear_caches()


@pytest.fixture(scope="session")
def binary_data():
    rng = np.random.RandomState(42)
    n = 600
    X = rng.randn(n, 6)
    logit = X[:, 0] * 2 + X[:, 1] - 0.5 * X[:, 2]
    y = (logit + 0.5 * rng.randn(n) > 0).astype(np.float64)
    return X, y


@pytest.fixture(scope="session")
def regression_data():
    rng = np.random.RandomState(17)
    n = 600
    X = rng.randn(n, 6)
    y = X[:, 0] * 3 + np.sin(2 * X[:, 1]) + 0.1 * rng.randn(n)
    return X, y


@pytest.fixture(scope="session")
def multiclass_data():
    rng = np.random.RandomState(7)
    n = 600
    X = rng.randn(n, 6)
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0.3).astype(int)).astype(np.float64)
    return X, y


@pytest.fixture(scope="session")
def rank_data():
    rng = np.random.RandomState(3)
    nq, qs = 40, 12
    y = rng.randint(0, 4, nq * qs).astype(np.float64)
    X = rng.randn(nq * qs, 5) + y[:, None] * 0.4
    group = np.full(nq, qs)
    return X, y, group


SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}
