"""One-program multi-model training (lightgbm_tpu.multitrain, ISSUE 7).

The load-bearing contract: model m of a ``train_many`` batch is
BIT-identical (model text + predictions) to the booster a standalone
``train(variants[m])`` with the same seeds produces — on the partition
and wave growers, quantized on/off, with bagging / feature_fraction /
balanced bagging / early stopping active — while all M models share one
binned dataset and ONE compiled grower program.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import ManyBooster, MultiTrainError, train_many
from lightgbm_tpu.multitrain.batched import BatchTrainer, batch_reject_reason
from lightgbm_tpu.multitrain.variants import (HOST_SWEEP, TRACED_SWEEP,
                                              group_variants,
                                              normalize_variants,
                                              structure_key)
from lightgbm_tpu.utils.random import host_rng, model_stream_seed

BASE = {"objective": "regression", "num_leaves": 15, "learning_rate": 0.1,
        "min_data_in_leaf": 5, "verbosity": -1}
N, F = 1200, 8


def _data(seed=0, n=N, f=F):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.1 * rng.randn(n)
    return X, y


def _fit_ref(params, X, y, rounds, valid=None):
    ds = lgb.Dataset(X, y)
    kw = {}
    if valid is not None:
        kw = dict(valid_sets=[lgb.Dataset(valid[0], valid[1], reference=ds)],
                  valid_names=["v0"])
    return lgb.train(params, ds, rounds, **kw)


def _assert_bit_identical(mb, vparams, X, y, rounds, valid=None):
    for m, v in enumerate(vparams):
        ref = _fit_ref({**BASE, **v}, X, y, rounds, valid)
        assert ref.model_to_string() == mb[m].model_to_string(), \
            f"model {m} ({v}) text differs from standalone train()"
        assert np.array_equal(ref.predict(X[:64]), mb[m].predict(X[:64]))
        assert ref.best_iteration == mb[m].best_iteration


# -- bit-identity vs the sequential loop ------------------------------------

@pytest.mark.parametrize("mode_params", [
    {},                                       # partition grower
    {"tree_grow_mode": "wave", "tpu_wave_size": 4},   # wave grower
    pytest.param({"use_quantized_grad": True},
                 marks=pytest.mark.slow),     # quantized (exact fallback)
    pytest.param({"tree_grow_mode": "wave", "tpu_wave_size": 4,
                  "use_quantized_grad": True},
                 marks=pytest.mark.slow),     # true int8 quantized wave
], ids=["partition", "wave", "quantized", "wave-quantized"])
def test_bit_identity_sweep(mode_params):
    X, y = _data()
    variants = [{"lambda_l1": 0.0}, {"lambda_l1": 0.7, "lambda_l2": 2.0},
                {"min_data_in_leaf": 20}]
    params = {**BASE, **mode_params}
    mb = train_many(params, lgb.Dataset(X, y), num_boost_round=5,
                    variants=variants)
    assert mb.fallback_indices == []
    _assert_bit_identical(mb, [{**mode_params, **v} for v in variants],
                          X, y, 5)


def test_bit_identity_pallas_wave():
    """ISSUE 8: the vmap gate is lifted — batched training rides the
    Pallas histogram kernels (interpret-mode off TPU) through jax's
    pallas_call batching rule, bit-identical per model to a standalone
    pallas train().  Small geometry: the interpret kernels are a
    correctness proxy, not a speed path, on this env."""
    X, y = _data()
    params = {**BASE, "num_leaves": 7, "tree_grow_mode": "wave",
              "tpu_wave_size": 2, "tpu_histogram_impl": "pallas",
              "tpu_speculative_ramp": False}
    variants = [{"lambda_l2": 0.0}, {"lambda_l2": 2.0}]
    mb = train_many(params, lgb.Dataset(X, y), num_boost_round=2,
                    variants=variants)
    assert mb.fallback_indices == []
    base = {k: v for k, v in params.items() if k not in BASE or k in
            ("num_leaves",)}
    _assert_bit_identical(mb, [{**base, **v} for v in variants], X, y, 2)


def test_bit_identity_bagging_and_feature_fraction():
    """The per-model RNG satellite: the batch's host-side bagging and
    feature_fraction draws must be the standalone draws, per model."""
    X, y = _data()
    params = {**BASE, "bagging_fraction": 0.7, "bagging_freq": 2,
              "feature_fraction": 0.6, "seed": 3}
    variants = [{}, {"bagging_seed": 99}, {"feature_fraction_seed": 17}]
    mb = train_many(params, lgb.Dataset(X, y), num_boost_round=6,
                    variants=variants)
    base_nofold = {k: v for k, v in params.items() if k not in BASE}
    _assert_bit_identical(mb, [{**base_nofold, **v} for v in variants],
                          X, y, 6)


@pytest.mark.slow
def test_bit_identity_balanced_bagging_binary():
    X, y = _data()
    yb = (y > 0).astype(np.float64)
    params = {**BASE, "objective": "binary", "pos_bagging_fraction": 0.8,
              "neg_bagging_fraction": 0.5, "bagging_freq": 1}
    mb = train_many(params, lgb.Dataset(X, yb), num_boost_round=5)
    ref = lgb.train(params, lgb.Dataset(X, yb), 5)
    assert ref.model_to_string() == mb[0].model_to_string()


def test_masked_early_stopping_each_model_stops_at_its_own_round():
    X, y = _data()
    Xv, yv = _data(seed=1, n=400)
    variants = [{"learning_rate": 0.5}, {"learning_rate": 0.1}]
    params = {**BASE, "early_stopping_round": 3}
    ds = lgb.Dataset(X, y)
    mb = train_many(params, ds, num_boost_round=30, variants=variants,
                    valid_sets=[lgb.Dataset(Xv, yv, reference=ds)],
                    valid_names=["v0"])
    refs = [_fit_ref({**params, **v}, X, y, 30, valid=(Xv, yv))
            for v in variants]
    for m, ref in enumerate(refs):
        assert mb[m].best_iteration == ref.best_iteration
        assert ref.model_to_string() == mb[m].model_to_string()
    # the fast model stops earlier than the slow one — genuinely
    # per-model stopping, not a shared round
    assert mb.best_iteration[0] != mb.best_iteration[1]
    # eval history matches the standalone early-stop run's metric keys
    assert "v0" in mb.eval_histories[0]


def test_bit_identity_pmap_sharded_model_axis():
    """M divisible by the device count engages the pmap-sharded model
    axis (each device grows M/k models); per-lane values are unchanged,
    so every extracted model stays bit-identical to standalone."""
    import jax
    if jax.local_device_count() < 2:
        pytest.skip("needs the multi-device CPU mesh")
    X, y = _data(n=800)
    k = jax.local_device_count()
    variants = [{"lambda_l1": 0.1 * i} for i in range(k)]
    tr = BatchTrainer([{**BASE, **v} for v in variants], lgb.Dataset(X, y))
    assert tr._shard, "M == device count must shard the model axis"
    mb = train_many(BASE, lgb.Dataset(X, y), num_boost_round=4,
                    variants=variants)
    _assert_bit_identical(mb, variants, X, y, 4)


# -- PR-20 lifted variants: GOSS / DART / multiclass / ranking ---------------

def _mc_data(seed=0, n=N, f=F):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    raw = X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(n)
    return X, np.digitize(raw, [-0.5, 0.5]).astype(np.float64)


def _rank_data(seed=0, n=N, f=F, gsize=30):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    rel = np.clip((X[:, 0] + 0.5 * rng.randn(n)) + 2, 0, 4).astype(int)
    groups = [gsize] * (n // gsize)
    groups[-1] += n - sum(groups)
    return X, rel.astype(np.float64), groups


@pytest.mark.slow
def test_bit_identity_goss_batch():
    """GOSS batches (PR 20): the per-lane host sampler is the SHARED
    goss_sample_np stream, so every lane's thinning equals its
    standalone run — top/other rates sweep host-side in one batch."""
    X, y = _data()
    params = {**BASE, "boosting": "goss", "learning_rate": 0.5}
    variants = [{"top_rate": 0.2, "other_rate": 0.1},
                {"top_rate": 0.3, "other_rate": 0.2},
                {"top_rate": 0.2, "other_rate": 0.1, "lambda_l1": 0.5}]
    mb = train_many(params, lgb.Dataset(X, y), num_boost_round=6,
                    variants=variants)
    assert mb.fallback_indices == []
    assert mb.num_groups == 1, "goss rate sweeps must share one batch"
    base = {"boosting": "goss", "learning_rate": 0.5}
    _assert_bit_identical(mb, [{**base, **v} for v in variants], X, y, 6)


@pytest.mark.slow
def test_bit_identity_dart_batch():
    """DART batches (PR 20): per-lane drop sets from the standalone
    (drop_seed, iteration) streams, Normalize as lane-masked axpys —
    drop knobs sweep host-side in one batch."""
    X, y = _data()
    params = {**BASE, "boosting": "dart"}
    variants = [{"drop_rate": 0.3, "drop_seed": 9},
                {"drop_rate": 0.6, "drop_seed": 9},
                {"drop_rate": 0.3, "drop_seed": 4,
                 "xgboost_dart_mode": True}]
    mb = train_many(params, lgb.Dataset(X, y), num_boost_round=7,
                    variants=variants)
    assert mb.fallback_indices == []
    assert mb.num_groups == 1, "dart drop sweeps must share one batch"
    _assert_bit_identical(mb, [{"boosting": "dart", **v}
                               for v in variants], X, y, 7)


@pytest.mark.slow
def test_bit_identity_multiclass_batch():
    """Multiclass batches (PR 20) as an (M, K) lane grid; composed with
    bagging + feature_fraction the per-lane draws still equal the
    standalone per-class streams."""
    X, y = _mc_data()
    params = {**BASE, "objective": "multiclass", "num_class": 3,
              "bagging_fraction": 0.7, "bagging_freq": 2,
              "feature_fraction": 0.8}
    variants = [{"lambda_l2": 0.0}, {"lambda_l2": 3.0},
                {"bagging_seed": 99}]
    mb = train_many(params, lgb.Dataset(X, y), num_boost_round=5,
                    variants=variants)
    assert mb.fallback_indices == []
    base = {k: v for k, v in params.items()
            if k not in BASE or k == "objective"}
    base["objective"] = "multiclass"
    _assert_bit_identical(mb, [{**base, **v} for v in variants], X, y, 5)


@pytest.mark.slow
def test_bit_identity_multiclass_early_stopping():
    X, y = _mc_data()
    Xv, yv = _mc_data(seed=1, n=400)
    params = {**BASE, "objective": "multiclass", "num_class": 3,
              "early_stopping_round": 3}
    variants = [{"learning_rate": 0.5}, {"learning_rate": 0.05}]
    ds = lgb.Dataset(X, y)
    mb = train_many(params, ds, num_boost_round=25, variants=variants,
                    valid_sets=[lgb.Dataset(Xv, yv, reference=ds)],
                    valid_names=["v0"])
    for m, v in enumerate(variants):
        p = {"objective": "multiclass", "num_class": 3,
             "early_stopping_round": 3, **v}
        ref = _fit_ref({**BASE, **p}, X, y, 25, valid=(Xv, yv))
        assert mb[m].best_iteration == ref.best_iteration
        assert ref.model_to_string() == mb[m].model_to_string()


@pytest.mark.slow
def test_ranking_structure_and_f32_parity():
    """Ranking batches (PR 20): the per-group lambdarank pass is
    lane-masked; trees match the standalone run structurally and
    predictions agree to f32 tolerance (the batched gradient pass
    reduces over the padded group axis in a different order)."""
    X, y, groups = _rank_data()
    params = {**BASE, "objective": "lambdarank",
              "metric": "ndcg", "ndcg_eval_at": [5]}
    variants = [{"lambda_l2": 0.0}, {"lambda_l2": 2.0}]
    mb = train_many(params, lgb.Dataset(X, y, group=groups),
                    num_boost_round=5, variants=variants)
    assert mb.fallback_indices == []
    for m, v in enumerate(variants):
        p = {**BASE, "objective": "lambdarank", "metric": "ndcg",
             "ndcg_eval_at": [5], **v}
        ref = lgb.train(p, lgb.Dataset(X, y, group=groups), 5)
        s_ref = [(t.split_feature.tolist(), t.threshold_bin.tolist())
                 for t in ref._gbdt.models]
        s_bat = [(t.split_feature.tolist(), t.threshold_bin.tolist())
                 for t in mb[m]._gbdt.models]
        assert s_ref == s_bat, f"ranking model {m} tree structure differs"
        np.testing.assert_allclose(ref.predict(X[:128]),
                                   mb[m].predict(X[:128]),
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.slow
@pytest.mark.parametrize("lift", [
    {"boosting": "goss", "learning_rate": 0.5},
    {"boosting": "dart", "drop_rate": 0.4, "drop_seed": 9},
    {"objective": "multiclass", "num_class": 3},
], ids=["goss", "dart", "multiclass"])
def test_cv_fold_parity_lifted(lift):
    """engine.cv routes the lifted variants through the batched fold
    driver; masked folds agree with the legacy compacted-subset loop to
    f32 reduction tolerance (multiclass amplifies via softmax -> wider
    rtol, same bar as the masked-subset parity test)."""
    if lift.get("objective") == "multiclass":
        X, y = _mc_data()
    else:
        X, y = _data()
    P = {**BASE, **lift}
    kw = dict(num_boost_round=5, nfold=3, seed=7)
    fast = lgb.cv(P, lgb.Dataset(X, y), **kw)
    slow = lgb.cv({**P, "tpu_cv_many": False}, lgb.Dataset(X, y), **kw)
    assert sorted(fast) == sorted(slow)
    for k in fast:
        np.testing.assert_allclose(fast[k], slow[k], rtol=2e-4, atol=1e-6,
                                   err_msg=k)


# -- one compile for M models ------------------------------------------------

def test_one_compile_for_m_models():
    """M models, ONE compiled grower program: the batch's jitted vmapped
    grower has exactly one executable in its cache after training, and
    growing the batch twice as wide reuses the same BatchTrainer program
    shape count (no per-model retrace)."""
    X, y = _data(n=600)
    variants = [{"lambda_l1": float(v)} for v in (0.0, 0.3, 0.9, 2.7)]
    tr = BatchTrainer([{**BASE, **v} for v in variants],
                      lgb.Dataset(X, y))
    tr.run(4)
    assert tr._vm_grow._cache_size() == 1, \
        "M models must share ONE compiled grower program"
    tr.finalize()


def test_traced_sweep_shares_structure_key():
    vs = normalize_variants(BASE, [{"lambda_l1": 0.1},
                                   {"lambda_l2": 5.0},
                                   {"learning_rate": 0.3},
                                   {"num_leaves": 31}])
    groups = group_variants(vs)
    # lambda/lr sweeps share a structure; num_leaves forces a new one
    assert groups == [[0, 1, 2], [3]]
    assert structure_key(vs[0]) == structure_key(vs[1])
    assert structure_key(vs[0]) != structure_key(vs[3])
    for f in ("lambda_l1", "lambda_l2", "min_sum_hessian_in_leaf",
              "min_data_in_leaf", "min_gain_to_split"):
        assert f in TRACED_SWEEP
    assert "learning_rate" in HOST_SWEEP


def test_structural_group_fallback_trains_everything():
    X, y = _data(n=600)
    variants = [{"lambda_l1": 0.5}, {"num_leaves": 7},
                {"cegb_penalty_split": 0.1}]  # CEGB cannot batch -> fallback
    mb = train_many(BASE, lgb.Dataset(X, y), num_boost_round=3,
                    variants=variants)
    assert sorted(mb.batched_indices) == [0, 1]
    assert mb.fallback_indices == [2]
    assert all(b is not None for b in mb.boosters)
    _assert_bit_identical(mb, variants[:2], X, y, 3)


def test_replicas_derive_decorrelated_seeds():
    X, y = _data(n=600)
    params = {**BASE, "bagging_fraction": 0.6, "bagging_freq": 1,
              "seed": 11, "bagging_seed": 5}
    mb = train_many(params, lgb.Dataset(X, y), num_boost_round=3,
                    replicas=3)
    # derived seeds are a pure function of (seed, model) and are
    # materialized into variant_params -> standalone reproducible.
    # model 0 keeps the base master seed (Config cascades sub-seeds
    # from a nonzero seed, so the master seed is what decorrelates)
    assert mb.variant_params[0]["seed"] == 11
    assert mb.variant_params[1]["seed"] == model_stream_seed(11, 1)
    assert mb.variant_params[1] != mb.variant_params[2]
    texts = {b.model_to_string() for b in mb}
    assert len(texts) == 3, "replicas must train decorrelated models"
    ref = lgb.train(mb.variant_params[2], lgb.Dataset(X, y), 3)
    assert ref.model_to_string() == mb[2].model_to_string()


def test_model_zero_keys_historical_stream():
    """model=0 must key Philox exactly like the historical 1-word form —
    every pre-existing single-model stream is unchanged."""
    a = host_rng(1234, 7).integers(0, 1 << 30, 16)
    b = host_rng(1234, 7, model=0).integers(0, 1 << 30, 16)
    assert np.array_equal(a, b)
    c = host_rng(1234, 7, model=1).integers(0, 1 << 30, 16)
    assert not np.array_equal(a, c)


# -- ManyBooster surface ------------------------------------------------------

def test_many_booster_container():
    X, y = _data(n=600)
    mb = train_many(BASE, lgb.Dataset(X, y), num_boost_round=3,
                    variants=[{"lambda_l1": v} for v in (0.0, 1.0)])
    assert isinstance(mb, ManyBooster)
    assert len(mb) == 2 and len(list(mb)) == 2
    stack = mb.predict(X[:32])
    assert stack.shape == (2, 32)
    assert np.array_equal(stack[1], mb[1].predict(X[:32]))


def test_sample_masks_against_shared_dataset():
    X, y = _data()
    rows0 = np.arange(0, N, 2)
    rows1 = np.arange(0, N, 3)
    masks = np.zeros((2, N), np.float32)
    masks[0, rows0] = 1.0
    masks[1, rows1] = 1.0
    mb = train_many(BASE, lgb.Dataset(X, y), num_boost_round=4,
                    sample_masks=masks)
    # each masked model only ever saw its rows: retraining standalone on
    # the SAME binned view (subset shares the parent's bin mappers)
    # gives a model whose predictions agree to f32 reduction tolerance
    parent = lgb.Dataset(X, y)
    parent.construct(lgb.Config(BASE))
    sub = parent.subset(rows0)
    assert sub.bin_mappers is parent.bin_mappers, \
        "folds must share the parent's bin mappers (binning done once)"
    ref = lgb.train(BASE, sub, 4)
    p1, p2 = ref.predict(X[:200]), mb[0].predict(X[:200])
    np.testing.assert_allclose(p1, p2, rtol=2e-4, atol=2e-5)


# -- engine.cv fast path ------------------------------------------------------

def test_cv_through_train_many_matches_fold_loop():
    X, y = _data()
    ds_kwargs = dict(num_boost_round=6, nfold=3, seed=7)
    fast = lgb.cv(BASE, lgb.Dataset(X, y), **ds_kwargs)
    slow = lgb.cv({**BASE, "tpu_cv_many": False}, lgb.Dataset(X, y),
                  **ds_kwargs)
    assert sorted(fast) == sorted(slow)
    for k in fast:
        np.testing.assert_allclose(fast[k], slow[k], rtol=5e-5, atol=1e-7,
                                   err_msg=k)


def test_cv_early_stopping_parity_and_cvbooster():
    X, y = _data()
    P = {**BASE, "early_stopping_round": 3, "learning_rate": 0.5}
    kw = dict(num_boost_round=35, nfold=3, seed=7, return_cvbooster=True)
    fast = lgb.cv(P, lgb.Dataset(X, y), **kw)
    slow = lgb.cv({**P, "tpu_cv_many": False}, lgb.Dataset(X, y), **kw)
    assert len(fast["valid l2-mean"]) == len(slow["valid l2-mean"])
    assert fast["cvbooster"].best_iteration == \
        slow["cvbooster"].best_iteration
    assert len(fast["cvbooster"].boosters) == 3
    # extracted fold boosters predict
    p = fast["cvbooster"].boosters[0].predict(X[:16])
    assert p.shape == (16,)


def test_cv_eval_train_metric_and_custom_folds():
    X, y = _data(n=800)
    folds = [(np.arange(0, 800, 2), np.arange(1, 800, 2)),
             (np.arange(1, 800, 2), np.arange(0, 800, 2))]
    fast = lgb.cv(BASE, lgb.Dataset(X, y), num_boost_round=4, folds=folds,
                  eval_train_metric=True)
    slow = lgb.cv({**BASE, "tpu_cv_many": False}, lgb.Dataset(X, y),
                  num_boost_round=4, folds=folds, eval_train_metric=True)
    assert sorted(fast) == sorted(slow)
    assert "train l2-mean" in fast
    for k in fast:
        np.testing.assert_allclose(fast[k], slow[k], rtol=5e-5, atol=1e-7,
                                   err_msg=k)


def test_cv_falls_back_on_custom_feval():
    X, y = _data(n=600)
    calls = []

    def feval(preds, ds):
        calls.append(1)
        return "dummy", 0.0, False

    out = lgb.cv(BASE, lgb.Dataset(X, y), num_boost_round=2, nfold=2,
                 feval=feval)
    assert calls, "custom feval must run (legacy path)"
    assert "valid dummy-mean" in out


# -- rejection / fallback edges ----------------------------------------------

def test_reject_reasons():
    X, y = _data(n=400)
    ds = lgb.Dataset(X, y)
    ds.construct(lgb.Config(BASE))
    assert batch_reject_reason(lgb.Config(BASE), ds) is None
    # the PR-20 lifts: goss / dart / multiclass / ranking all batch now
    for lifted in ({"boosting": "goss"}, {"boosting": "dart"},
                   {"objective": "multiclass", "num_class": 3},
                   {"objective": "lambdarank"}):
        assert batch_reject_reason(lgb.Config({**BASE, **lifted}), ds) \
            is None, f"{lifted} must no longer reject"
    # every REMAINING reject string, hit explicitly (coverage: a new
    # reject added without a test here is a lint failure by convention)
    assert "tree_learner" in batch_reject_reason(
        lgb.Config({**BASE, "tree_learner": "data"}), ds)
    assert "boosting=rf" in batch_reject_reason(
        lgb.Config({**BASE, "boosting": "rf", "bagging_freq": 1,
                    "bagging_fraction": 0.5}), ds)
    assert "objective=none" in batch_reject_reason(
        lgb.Config({**BASE, "objective": "none"}), ds)
    assert "linear_tree" in batch_reject_reason(
        lgb.Config({**BASE, "linear_tree": True}), ds)
    assert "CEGB" in batch_reject_reason(
        lgb.Config({**BASE, "cegb_penalty_split": 0.1}), ds)


def test_strict_mode_and_fallback_counter():
    """The never-silent contract: strict=True raises instead of going
    sequential, and EVERY fallback bumps
    multitrain_fallback_total{reason} with the bounded reason prefix."""
    from lightgbm_tpu.telemetry.metrics import default_registry
    X, y = _data(n=400)
    with pytest.raises(MultiTrainError, match="CEGB"):
        train_many({**BASE, "cegb_penalty_split": 0.1}, lgb.Dataset(X, y),
                   num_boost_round=2, strict=True)
    reg = default_registry()
    ctr = reg.counter("multitrain_fallback_total",
                      "train_many models that fell back to sequential "
                      "train(), by structural reason", labels=("reason",))
    c0 = ctr.value(reason="CEGB penalties")
    train_many({**BASE, "cegb_penalty_split": 0.1}, lgb.Dataset(X, y),
               num_boost_round=2)
    # bounded label: the free text after " (" is stripped
    assert ctr.value(reason="CEGB penalties") == c0 + 1
    req = reg.counter("multitrain_models_requested_total",
                      "models requested through train_many "
                      "(batched or not)")
    assert req.value() >= 2


def test_fallback_rate_slo_declared_and_covered():
    """The multitrain/fallback_rate SLO keys to registered series (the
    slo_cover lint runs this fleet-wide; asserted here so the contract
    is local to the subsystem too)."""
    from lightgbm_tpu.analysis.slo_cover import check_slo_coverage
    from lightgbm_tpu.telemetry.slo import all_slos
    assert "multitrain/fallback_rate" in all_slos()
    bad = [v for v in check_slo_coverage()
           if "multitrain" in v.site]
    assert bad == []


def test_masked_is_unbalance_rejected():
    """is_unbalance derives label_weight from the FULL dataset's pos/neg
    counts; a fold-masked model's standalone counterpart derives it from
    its own rows — must reject, and cv() must fall back to the legacy
    fold loop (which subsets per fold and reweights correctly)."""
    X, y = _data(n=600)
    yb = (y > 0).astype(np.float64)
    masks = np.ones((2, 600), np.float32)
    masks[0, ::3] = 0.0
    with pytest.raises(MultiTrainError, match="is_unbalance"):
        BatchTrainer([{**BASE, "objective": "binary",
                       "is_unbalance": True}] * 2,
                     lgb.Dataset(X, yb), sample_masks=masks)
    # unmasked batches share the full metadata with their standalone
    # counterparts, so is_unbalance stays batchable there
    out = lgb.cv({**BASE, "objective": "binary", "is_unbalance": True},
                 lgb.Dataset(X, yb), num_boost_round=2, nfold=2)
    assert len(out["valid binary_logloss-mean"]) == 2


def test_allow_fallback_false_raises():
    X, y = _data(n=400)
    with pytest.raises(MultiTrainError):
        train_many({**BASE, "cegb_penalty_split": 0.1}, lgb.Dataset(X, y),
                   num_boost_round=2, allow_fallback=False)


def test_variant_columns_and_length_mismatch():
    vs = normalize_variants(BASE, {"lambda_l1": [0.0, 1.0],
                                   "learning_rate": [0.1, 0.2]})
    assert len(vs) == 2 and vs[1]["lambda_l1"] == 1.0
    with pytest.raises(ValueError):
        normalize_variants(BASE, {"lambda_l1": [0.0, 1.0],
                                  "learning_rate": [0.1]})
    with pytest.raises(ValueError):
        normalize_variants(BASE, [{}], replicas=2)


# -- checkpoint interop (chaos) ----------------------------------------------

@pytest.mark.chaos
def test_train_many_rejects_checkpointing(tmp_path):
    """Never a silent bad resume: checkpoint/resume params raise a typed
    CheckpointError in train_many instead of training without the fault
    tolerance they asked for."""
    from lightgbm_tpu import CheckpointError
    X, y = _data(n=400)
    for bad in ({"checkpoint_dir": str(tmp_path)},
                {"snapshot_freq": 2},
                {"resume": "latest"}):
        with pytest.raises(CheckpointError, match="train_many"):
            train_many({**BASE, **bad}, lgb.Dataset(X, y),
                       num_boost_round=2)


@pytest.mark.chaos
def test_cv_with_checkpoint_params_falls_back_to_fold_loop(tmp_path):
    """engine.cv never checkpointed; with checkpoint params present the
    fast path steps aside and the legacy loop runs unchanged."""
    X, y = _data(n=400)
    out = lgb.cv({**BASE, "snapshot_freq": 2}, lgb.Dataset(X, y),
                 num_boost_round=2, nfold=2)
    assert "valid l2-mean" in out and len(out["valid l2-mean"]) == 2


@pytest.mark.chaos
def test_train_many_fault_injection_propagates():
    from lightgbm_tpu.resilience.faults import InjectedFault, faults
    X, y = _data(n=400)
    faults.clear()
    try:
        faults.configure("crash_at_iter=1")
        with pytest.raises(InjectedFault):
            train_many(BASE, lgb.Dataset(X, y), num_boost_round=4)
    finally:
        faults.clear()


# -- telemetry ----------------------------------------------------------------

def test_telemetry_counters_and_train_record():
    from lightgbm_tpu.telemetry.metrics import default_registry
    X, y = _data(n=400)
    reg = default_registry()
    c0 = reg.counter("multitrain_models_total",
                     "models trained on the vmapped model axis").value()
    mb = train_many(BASE, lgb.Dataset(X, y), num_boost_round=3,
                    variants=[{"lambda_l1": v} for v in (0.0, 1.0, 2.0)])
    c1 = reg.counter("multitrain_models_total",
                     "models trained on the vmapped model axis").value()
    assert c1 - c0 == 3
    # per-model TrainRecords surface through the extracted boosters
    rec = mb[1].train_record
    assert rec.meta["multitrain_model_index"] == 1
    assert rec.meta["multitrain_models"] == 3
    assert rec.snapshot()["num_trees"] == 3


# -- sklearn sweep ------------------------------------------------------------

def test_grid_search_cv_many_regressor():
    pytest.importorskip("sklearn")
    from lightgbm_tpu.multitrain import GridSearchCVMany
    from lightgbm_tpu.sklearn import LGBMRegressor
    X, y = _data(n=800)
    gs = GridSearchCVMany(
        LGBMRegressor(n_estimators=8, num_leaves=15, min_child_samples=5),
        {"reg_lambda": [0.0, 1.0], "learning_rate": [0.1, 0.3]}, cv=3)
    gs.fit(X, y)
    assert len(gs.cv_results_["params"]) == 4
    assert gs.cv_results_["mean_test_score"].shape == (4,)
    assert set(gs.best_params_) == {"reg_lambda", "learning_rate"}
    assert gs.best_score_ == max(gs.cv_results_["mean_test_score"])
    assert 1 in gs.cv_results_["rank_test_score"]
    # refit estimator predicts on full data
    assert gs.predict(X[:8]).shape == (8,)
    assert gs.score(X, y) > 0.8


def test_grid_search_cv_many_classifier_matches_sequential():
    pytest.importorskip("sklearn")
    from sklearn.model_selection import KFold
    from lightgbm_tpu.multitrain import GridSearchCVMany
    from lightgbm_tpu.sklearn import LGBMClassifier
    X, y = _data(n=800)
    yb = (y > 0).astype(int)
    grid = {"reg_lambda": [0.0, 5.0]}
    est = LGBMClassifier(n_estimators=8, num_leaves=7, min_child_samples=5)
    gs = GridSearchCVMany(est, grid, cv=KFold(3), refit=False)
    gs.fit(X, yb)
    assert gs.cv_results_["mean_test_score"].shape == (2,)
    assert 0.5 < gs.best_score_ <= 1.0
