"""Quantized-gradient training tests (ops/quantize.py, the q8 kernel,
wave-grower integration).

Mirrors the reference's quantized-training coverage
(tests/python_package_test/test_engine.py test_quantized_training):
quality stays close to exact training, and the TPU specifics hold —
integer histogram exactness, deterministic rounding parity between the
serial and data-parallel wave growers, and exact leaf renewal."""

import jax
import jax.numpy as jnp
import numpy as np

import lightgbm_tpu as lgb


def _binary(n=4000, f=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    w = rng.randn(f)
    y = ((X @ w + 0.5 * rng.randn(n)) > 0).astype(np.float64)
    return X, y


def _logloss(y, p):
    p = np.clip(p, 1e-9, 1 - 1e-9)
    return -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))


def _params(**kw):
    p = {"objective": "binary", "num_leaves": 31, "max_bin": 63,
         "learning_rate": 0.2, "verbosity": -1, "min_data_in_leaf": 20,
         "tree_grow_mode": "wave"}
    p.update(kw)
    return p


def test_q8_kernel_interpret_exact():
    """Pallas q8 kernel (interpret mode) == numpy integer bincount."""
    from lightgbm_tpu.ops.histogram_pallas import (
        Q_LEAF_CHANNELS, build_histogram_pallas_leaves_q8, pad_rows)
    rng = np.random.RandomState(0)
    f, b = 5, 64
    n = pad_rows(5000)
    bins = rng.randint(0, b, (f, n)).astype(np.uint8)
    gq = rng.randint(-127, 128, n).astype(np.int8)
    hq = rng.randint(0, 128, n).astype(np.int8)
    ch = rng.randint(-1, Q_LEAF_CHANNELS, n).astype(np.int8)
    cnt = (ch >= 0).astype(np.int8)
    wch = np.zeros((8, n), np.int8)
    wch[0], wch[1], wch[2] = gq, hq, cnt

    hist = np.asarray(build_histogram_pallas_leaves_q8(
        jnp.asarray(bins), jnp.asarray(wch), jnp.asarray(ch), num_bins=b,
        interpret=True))
    assert hist.shape == (Q_LEAF_CHANNELS, f, b, 3)
    assert hist.dtype == np.int32

    for q in (0, 7, Q_LEAF_CHANNELS - 1):
        m = ch == q
        for j in (0, f - 1):
            ref_g = np.bincount(bins[j][m], weights=gq[m].astype(np.float64),
                                minlength=b)
            ref_h = np.bincount(bins[j][m], weights=hq[m].astype(np.float64),
                                minlength=b)
            ref_c = np.bincount(bins[j][m], minlength=b)
            np.testing.assert_array_equal(hist[q, j, :, 0], ref_g[:b])
            np.testing.assert_array_equal(hist[q, j, :, 1], ref_h[:b])
            np.testing.assert_array_equal(hist[q, j, :, 2], ref_c[:b])


def test_bf16_leaves_kernel_interpret_exact():
    """Exact bf16 hi/lo leaves kernel (interpret) == numpy bincount to
    f32 precision — guards the feature-major rhs-T layout."""
    from lightgbm_tpu.ops.histogram_pallas import (
        LEAF_CHANNELS, build_histogram_pallas_leaves, pack_weights8,
        pad_rows)
    rng = np.random.RandomState(2)
    f, b = 5, 64
    n = pad_rows(5000)
    bins = rng.randint(0, b, (f, n)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.abs(rng.randn(n)).astype(np.float32)
    mask = (rng.rand(n) < 0.8).astype(np.float32)
    ch = rng.randint(-1, LEAF_CHANNELS, n).astype(np.int32)

    w8 = pack_weights8(jnp.asarray(grad), jnp.asarray(hess),
                       jnp.asarray(mask))
    assert np.asarray(w8).shape == (8, n)
    hist = np.asarray(build_histogram_pallas_leaves(
        jnp.asarray(bins), w8, jnp.asarray(ch), num_bins=b,
        interpret=True))
    assert hist.shape == (LEAF_CHANNELS, f, b, 3)
    gm = (grad * mask).astype(np.float64)
    hm = (hess * mask).astype(np.float64)
    for q in (0, LEAF_CHANNELS - 1):
        m = ch == q
        for j in (0, f - 1):
            ref_g = np.bincount(bins[j][m], weights=gm[m], minlength=b)
            ref_h = np.bincount(bins[j][m], weights=hm[m], minlength=b)
            ref_c = np.bincount(bins[j][m],
                                weights=(mask[m] > 0).astype(np.float64),
                                minlength=b)
            np.testing.assert_allclose(hist[q, j, :, 0], ref_g[:b],
                                       rtol=1e-5, atol=1e-4)
            np.testing.assert_allclose(hist[q, j, :, 1], ref_h[:b],
                                       rtol=1e-5, atol=1e-4)
            np.testing.assert_array_equal(hist[q, j, :, 2], ref_c[:b])


def test_wave_row_update_kernel_matches_reference():
    """Pallas row-update kernel (interpret) == the masked-where loop."""
    from lightgbm_tpu.ops.histogram_pallas import (pad_rows,
                                                   wave_row_update_pallas)
    rng = np.random.RandomState(5)
    w = 11
    n = pad_rows(9000)
    cols = rng.randint(0, 250, (w, n)).astype(np.uint8)
    rl = rng.randint(0, 60, n).astype(np.int32)
    thr = rng.randint(0, 250, w)
    nanb = np.where(rng.rand(w) < 0.5, -1, 249)
    dleft = rng.randint(0, 2, w)
    small = rng.randint(0, 2, w)
    selL = rng.choice(60, w, replace=False)
    newid = 60 + np.arange(w)
    act = rng.randint(0, 2, w)
    tab = np.stack([thr, nanb, dleft, small, selL, newid, act,
                    np.zeros(w)]).astype(np.int32)

    rl_ref = rl.copy()
    ch_ref = np.full(n, -1, np.int8)
    for j in range(w):
        go_left = np.where(cols[j] == nanb[j], dleft[j] > 0,
                           cols[j] <= thr[j])
        upd = (rl_ref == selL[j]) & (act[j] > 0)
        ch_ref[upd & (go_left == (small[j] > 0))] = j
        rl_ref[upd & ~go_left] = newid[j]

    rl_new, ch = wave_row_update_pallas(
        jnp.asarray(cols), jnp.asarray(rl), jnp.asarray(tab),
        interpret=True)
    np.testing.assert_array_equal(np.asarray(rl_new), rl_ref)
    np.testing.assert_array_equal(np.asarray(ch), ch_ref)


def test_quantize_wch_levels_and_unbiasedness():
    from lightgbm_tpu.ops.quantize import quant_levels, quantize_wch
    assert quant_levels(4) == (2, 4)
    assert quant_levels(254) == (127, 127)
    assert quant_levels(100000) == (127, 127)

    rng = np.random.RandomState(0)
    n = 20000
    grad = rng.randn(n).astype(np.float32)
    hess = np.abs(rng.randn(n)).astype(np.float32)
    bag = np.ones(n, np.float32)
    gs = jnp.float32(np.abs(grad).max() / 127)
    hs = jnp.float32(hess.max() / 127)
    wch = np.asarray(quantize_wch(
        jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(bag), gs, hs,
        jax.random.PRNGKey(0), gq_max=127, hq_max=127, stochastic=True))
    assert wch.dtype == np.int8 and wch.shape == (8, n)
    # stochastic rounding is unbiased: the dequantized mean tracks the
    # true mean well within the quantization noise floor
    est = wch[0].astype(np.float64).mean() * float(gs)
    assert abs(est - grad.mean()) < 4 * float(gs) / np.sqrt(n) + 1e-6
    # hessian levels in range, counts exact
    assert wch[1].min() >= 0 and wch[1].max() <= 127
    assert (wch[2] == 1).all()
    # masked rows contribute nothing
    bag2 = bag.copy()
    bag2[:1000] = 0.0
    wch2 = np.asarray(quantize_wch(
        jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(bag2), gs, hs,
        jax.random.PRNGKey(0), gq_max=127, hq_max=127, stochastic=True))
    assert (wch2[:3, :1000] == 0).all()


def test_quantized_quality_close_to_exact():
    X, y = _binary()
    ll_exact = _logloss(y, lgb.train(
        _params(), lgb.Dataset(X, y), num_boost_round=10).predict(X))
    ll_q = _logloss(y, lgb.train(
        _params(use_quantized_grad=True, num_grad_quant_bins=254,
                quant_train_renew_leaf=True),
        lgb.Dataset(X, y), num_boost_round=10).predict(X))
    assert ll_q < ll_exact * 1.05 + 1e-3
    # the reference's own default: 4 quant bins still trains usefully
    ll_q4 = _logloss(y, lgb.train(
        _params(use_quantized_grad=True, num_grad_quant_bins=4,
                quant_train_renew_leaf=True),
        lgb.Dataset(X, y), num_boost_round=10).predict(X))
    assert ll_q4 < _logloss(y, np.full_like(y, y.mean())) * 0.9


def test_quantized_deterministic_same_seed():
    X, y = _binary(n=2000)
    p = _params(use_quantized_grad=True, num_grad_quant_bins=64, seed=11)
    pred1 = lgb.train(p, lgb.Dataset(X, y), num_boost_round=5).predict(X)
    pred2 = lgb.train(p, lgb.Dataset(X, y), num_boost_round=5).predict(X)
    np.testing.assert_array_equal(pred1, pred2)


def test_quantized_renew_leaf_values_exact():
    """With renewal on, leaf values equal the exact-gradient optimum for
    the quantized tree's own structure: one tree, compare against leaf
    values recomputed from true gradients and the tree's leaf
    assignment."""
    X, y = _binary(n=3000)
    lam = 0.01
    p = _params(use_quantized_grad=True, num_grad_quant_bins=254,
                quant_train_renew_leaf=True, stochastic_rounding=False,
                learning_rate=1.0, lambda_l2=lam, num_leaves=15)
    bst = lgb.train(p, lgb.Dataset(X, y), num_boost_round=1)
    pred_raw = bst.predict(X, raw_score=True)
    leaf_idx = bst.predict(X, pred_leaf=True).reshape(-1)
    # binary objective (sigmoid=1) from the constant init score
    init = bst._gbdt.init_scores
    init = float(init[0]) if init is not None else 0.0
    p0 = 1.0 / (1.0 + np.exp(-init))
    g = p0 - y
    h = p0 * (1 - p0) * np.ones_like(y)
    got, want = [], []
    for leaf in np.unique(leaf_idx):
        m = leaf_idx == leaf
        opt = -g[m].sum() / (h[m].sum() + lam)
        raw = pred_raw[m][0] - init
        got.append(raw)
        want.append(opt)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_quantized_dp_wave_matches_serial():
    """Deterministic rounding: the 8-shard DP wave grower reproduces the
    serial quantized model exactly (global scales via pmax, int32 psum)."""
    X, y = _binary(n=2400, f=6)
    kw = dict(use_quantized_grad=True, num_grad_quant_bins=254,
              stochastic_rounding=False, quant_train_renew_leaf=True,
              num_leaves=15)
    pred_s = lgb.train(_params(**kw), lgb.Dataset(X, y),
                       num_boost_round=5).predict(X)
    pred_d = lgb.train(_params(tree_learner="data", **kw),
                       lgb.Dataset(X, y), num_boost_round=5).predict(X)
    np.testing.assert_allclose(pred_d, pred_s, atol=2e-5, rtol=2e-5)


def test_quantized_with_goss_and_cats():
    rng = np.random.RandomState(3)
    n = 3000
    Xc = rng.randint(0, 12, (n, 2)).astype(np.float32)
    Xn = rng.randn(n, 4).astype(np.float32)
    X = np.concatenate([Xn, Xc], axis=1)
    y = ((X[:, 0] + (Xc[:, 0] % 3 == 1) * 1.5 +
          0.4 * rng.randn(n)) > 0.5).astype(np.float64)
    p = _params(use_quantized_grad=True, num_grad_quant_bins=254,
                quant_train_renew_leaf=True, data_sample_strategy="goss",
                categorical_feature=[4, 5])
    bst = lgb.train(p, lgb.Dataset(X, y), num_boost_round=8)
    ll = _logloss(y, bst.predict(X))
    assert ll < _logloss(y, np.full_like(y, y.mean())) * 0.9


def test_quantized_warns_and_falls_back_off_wave(capsys):
    X, y = _binary(n=1000)
    p = _params(use_quantized_grad=True, tree_grow_mode="partition",
                verbosity=1)
    bst = lgb.train(p, lgb.Dataset(X, y), num_boost_round=3)
    assert np.isfinite(bst.predict(X)).all()


def test_quantized_with_efb_sparse():
    """Quantized histograms over EFB bundle columns (bundle-space bins
    feed the q8 kernel emulation; sparse ingest stays sparse)."""
    import scipy.sparse as sp
    rng = np.random.RandomState(9)
    n = 3000
    # 8 one-hot groups of 5 mutually-exclusive columns: truly disjoint
    # sparsity, the shape EFB exists for
    cats = rng.randint(0, 5, (n, 8))
    Xd = np.zeros((n, 40))
    for g in range(8):
        Xd[np.arange(n), g * 5 + cats[:, g]] = rng.rand(n) + 0.5
    y = ((Xd[:, 0] + Xd[:, 7] - Xd[:, 12] + 0.3 * rng.randn(n)) > 0.2
         ).astype(np.float64)
    X = sp.csr_matrix(Xd)
    p = _params(use_quantized_grad=True, num_grad_quant_bins=254,
                quant_train_renew_leaf=True, num_leaves=15)
    bst = lgb.train(p, lgb.Dataset(X, y), 8)
    assert bst._gbdt.train_set.efb is not None, "EFB should engage"
    ll_q = _logloss(y, bst.predict(Xd))
    bste = lgb.train(_params(num_leaves=15), lgb.Dataset(X, y), 8)
    ll_e = _logloss(y, bste.predict(Xd))
    assert ll_q < ll_e * 1.08 + 1e-3


def test_quantized_multiclass_and_dart():
    rng = np.random.RandomState(12)
    n = 2500
    X = rng.randn(n, 6).astype(np.float32)
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0.4).astype(int)
         ).astype(np.float64)
    p = _params(objective="multiclass", num_class=3,
                use_quantized_grad=True, num_grad_quant_bins=254,
                quant_train_renew_leaf=True, num_leaves=15)
    bst = lgb.train(p, lgb.Dataset(X, y), 6)
    proba = bst.predict(X)
    assert proba.shape == (n, 3)
    assert np.allclose(proba.sum(1), 1.0, atol=1e-5)
    acc = (proba.argmax(1) == y).mean()
    assert acc > 0.7, acc

    yb = (y > 0).astype(np.float64)
    pd = _params(boosting="dart", use_quantized_grad=True,
                 num_grad_quant_bins=254, num_leaves=15)
    bd = lgb.train(pd, lgb.Dataset(X, yb), 8)
    assert np.isfinite(bd.predict(X)).all()
