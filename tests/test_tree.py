"""Tree learner structural tests: partition/count consistency, determinism,
and agreement between the binned device walk, the raw device walk, and the
host reference predictor."""

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.models.tree import TreeBatch, predict_binned, predict_raw

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


def test_tree_counts_consistent(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary"}, lgb.Dataset(X, y), 3)
    for tree in bst._gbdt.models:
        nl = tree.num_leaves
        # leaf counts sum to total rows
        assert tree.leaf_count[:nl].sum() == len(y)
        # each internal node's count equals its children's counts
        for i in range(nl - 1):
            def cnt(c):
                return (tree.leaf_count[~c] if c < 0
                        else tree.internal_count[c])
            assert tree.internal_count[i] == cnt(tree.left_child[i]) + \
                cnt(tree.right_child[i])


def test_determinism(binary_data):
    X, y = binary_data
    p1 = lgb.train({**SMALL, "objective": "binary"},
                   lgb.Dataset(X, y), 5).predict(X)
    p2 = lgb.train({**SMALL, "objective": "binary"},
                   lgb.Dataset(X, y), 5).predict(X)
    np.testing.assert_array_equal(p1, p2)


def test_walks_agree(binary_data):
    """Binned walk (training) == raw walk (inference) == host predictor."""
    import jax.numpy as jnp
    X, y = binary_data
    ds = lgb.Dataset(X, y)
    bst = lgb.train({**SMALL, "objective": "binary"}, ds, 4)
    gbdt = bst._gbdt
    batch = TreeBatch(gbdt.models)
    raw_dev = np.asarray(predict_raw(
        batch, jnp.asarray(X[:, gbdt.train_set.used_feature_map], jnp.float32)))
    binned_dev = np.asarray(predict_binned(
        batch, jnp.asarray(gbdt.train_set.X_binned)))
    host = sum(t.predict(X[:, gbdt.train_set.used_feature_map])
               for t in gbdt.models)
    np.testing.assert_allclose(raw_dev, binned_dev, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(raw_dev, host, rtol=1e-5, atol=1e-6)


def test_min_data_in_leaf_respected(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary", "min_data_in_leaf": 50},
                    lgb.Dataset(X, y), 3)
    for tree in bst._gbdt.models:
        assert (tree.leaf_count[:tree.num_leaves] >= 50).all()


def test_num_leaves_limit(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary", "num_leaves": 4},
                    lgb.Dataset(X, y), 3)
    for tree in bst._gbdt.models:
        assert tree.num_leaves <= 4


def test_stops_when_no_gain():
    # constant-ish labels: after a couple of trees no split improves
    rng = np.random.RandomState(0)
    X = rng.randn(200, 3)
    y = np.ones(200)
    bst = lgb.train({**SMALL, "objective": "regression"}, lgb.Dataset(X, y), 5)
    p = bst.predict(X)
    np.testing.assert_allclose(p, 1.0, atol=1e-5)


def test_dense_walk_matches_sequential_walk():
    """The MXU dense walk (path-matrix formulation) must reproduce the
    sequential gather walk bit-for-bit on numeric trees (incl. NaN
    routing and linear leaves)."""
    import jax.numpy as jnp
    import lightgbm_tpu as lgb
    from lightgbm_tpu.models.tree import TreeBatch, _walk_raw, predict_raw

    rng = np.random.RandomState(3)
    X = rng.randn(2000, 6).astype(np.float32)
    X[rng.rand(2000, 6) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1]) > 0
         ).astype(np.float64)
    for extra in ({}, {"linear_tree": True, "objective": "regression"}):
        p = {"objective": "binary", "num_leaves": 31, "verbosity": -1,
             "min_data_in_leaf": 5, **extra}
        bst = lgb.train(p, lgb.Dataset(X, np.nan_to_num(X[:, 0]) * 2
                                       if extra else y), 5)
        batch = TreeBatch(bst._gbdt.models)
        assert not batch.has_cat
        Xd = jnp.asarray(X)
        dense = np.asarray(predict_raw(batch, Xd))
        # sequential reference: per-tree gather walk summed
        seq = np.zeros(len(X), np.float32)
        seq_leaves = []
        for t in range(batch.num_trees):
            tf = tuple(a[t] for a in
                       (batch.split_feature, batch.threshold,
                        batch.cat_words, batch.decision_type,
                        batch.left_child, batch.right_child,
                        batch.leaf_value, batch.num_leaves))
            val, leaf = _walk_raw(Xd, *tf)
            seq_leaves.append(np.asarray(leaf))
            seq += np.asarray(val)
        if not batch.has_linear:
            np.testing.assert_allclose(dense, seq, rtol=1e-6, atol=1e-7)
        # leaf resolution identical (drives linear evaluation too)
        from lightgbm_tpu.models.tree import _walk_raw_dense
        for t in (0, batch.num_trees - 1):
            tfd = tuple(a[t] for a in
                        (batch.split_feature, batch.threshold,
                         batch.decision_type, batch.path_dir,
                         batch.plen_right, batch.plen_total,
                         batch.leaf_value))
            _, leaf_d = _walk_raw_dense(Xd, *tfd)
            np.testing.assert_array_equal(np.asarray(leaf_d),
                                          seq_leaves[t])


def test_binned_dense_walk_matches_sequential():
    """On-device path-matrix walk == the sequential binned walk for
    grower-produced trees (incl. the NaN bin)."""
    import jax.numpy as jnp
    import lightgbm_tpu as lgb
    from lightgbm_tpu.models.tree import _walk_binned, _walk_binned_dense

    rng = np.random.RandomState(9)
    X = rng.randn(3000, 5).astype(np.float32)
    X[rng.rand(3000, 5) < 0.1] = np.nan
    y = (np.nan_to_num(X[:, 0]) - np.nan_to_num(X[:, 1]) > 0).astype(
        np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 31,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(X, y), 5)
    gb = bst._gbdt
    bins = gb.X_dev
    assert gb._walk_dense_ok
    for tree in gb.models:
        args = (jnp.asarray(tree.split_feature),
                jnp.asarray(tree.threshold_bin),
                jnp.asarray(tree.nan_bin),
                jnp.zeros((len(tree.split_feature), 1), jnp.bool_),
                jnp.asarray(tree.decision_type.astype(np.int32)),
                jnp.asarray(tree.left_child),
                jnp.asarray(tree.right_child),
                jnp.asarray(tree.leaf_value.astype(np.float32)),
                jnp.asarray(tree.num_leaves, jnp.int32))
        seq = np.asarray(_walk_binned(bins, *args))
        dense = np.asarray(_walk_binned_dense(
            bins, *(args[:3] + args[4:])))
        np.testing.assert_allclose(dense, seq, rtol=1e-6, atol=1e-7)


def test_efb_dense_binned_walk_matches_sequential():
    """EFB bundle-space dense walk == the sequential EFB walk."""
    import jax.numpy as jnp
    import lightgbm_tpu as lgb
    from lightgbm_tpu.models.tree import (_walk_binned_dense_efb,
                                          _walk_binned_efb)

    rng = np.random.RandomState(11)
    n = 4000
    cats = rng.randint(0, 5, (n, 8))
    X = np.zeros((n, 40), np.float32)
    for g in range(8):
        X[np.arange(n), g * 5 + cats[:, g]] = rng.rand(n) + 0.5
    y = ((X[:, 0] + X[:, 7] - X[:, 12] > 0.8)).astype(np.float64)
    import scipy.sparse as sp
    bst = lgb.train({"objective": "binary", "num_leaves": 31, "max_bin": 63,
                     "verbosity": -1, "min_data_in_leaf": 5},
                    lgb.Dataset(sp.csr_matrix(X), y), 4)
    gb = bst._gbdt
    assert gb._efb_walk is not None and gb._walk_dense_ok
    bins = gb.X_dev
    for tree in gb.models:
        args = (jnp.asarray(tree.split_feature),
                jnp.asarray(tree.threshold_bin),
                jnp.asarray(tree.nan_bin),
                jnp.zeros((len(tree.split_feature), 1), jnp.bool_),
                jnp.asarray(tree.decision_type.astype(np.int32)),
                jnp.asarray(tree.left_child),
                jnp.asarray(tree.right_child),
                jnp.asarray(tree.leaf_value.astype(np.float32)),
                jnp.asarray(tree.num_leaves, jnp.int32))
        seq = np.asarray(_walk_binned_efb(bins, gb._efb_walk, *args))
        dense = np.asarray(_walk_binned_dense_efb(
            bins, gb._efb_walk, *(args[:3] + args[4:])))
        np.testing.assert_allclose(dense, seq, rtol=1e-6, atol=1e-7)
