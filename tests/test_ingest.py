"""Out-of-core ingestion subsystem: sources, streaming sketch binning,
StreamedDataset, and the streamed-vs-in-core identity contract on the
engine.train (hbm) route."""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.binning import (find_bin, find_bin_from_summary,
                                  merge_column_summaries, summarize_column)
from lightgbm_tpu.ingest import (ArraySource, BinningSketch, CSVSource,
                                 NumpyMmapSource, StreamedDataset,
                                 SyntheticSource, sample_row_indices)


def _data(n=3001, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    if f > 2:
        X[:, 2] = np.where(rng.rand(n) < 0.3, 0.0, X[:, 2])
    if f > 3:
        X[:, 3] = np.where(rng.rand(n) < 0.1, np.nan, X[:, 3])
    if f > 4:
        X[:, 4] = rng.randint(0, 9, n)
    y = (X[:, 0] + np.nan_to_num(X[:, 1]) * 0.5 +
         rng.randn(n) * 0.5 > 0).astype(np.float64)
    return X, y


def _mappers_equal(a, b):
    assert len(a) == len(b)
    for j, (ma, mb) in enumerate(zip(a, b)):
        assert ma.num_bin == mb.num_bin, j
        assert ma.is_categorical == mb.is_categorical, j
        assert ma.missing_type == mb.missing_type, j
        assert ma.default_bin == mb.default_bin, j
        assert ma.most_freq_bin == mb.most_freq_bin, j
        assert ma.forced_trivial == mb.forced_trivial, j
        if ma.bin_upper_bound is not None or mb.bin_upper_bound is not None:
            assert np.array_equal(ma.bin_upper_bound, mb.bin_upper_bound), j
        assert ma.cat_to_bin == mb.cat_to_bin, j


# ---------------------------------------------------------------------------
# summaries / sketch
# ---------------------------------------------------------------------------

def test_summary_merge_matches_one_shot():
    rng = np.random.RandomState(1)
    vals = np.concatenate([rng.randn(500), np.zeros(100),
                           np.full(30, np.nan), rng.randn(200) * 1e-3])
    rng.shuffle(vals)
    one = find_bin(vals, max_bin=63)
    parts = [summarize_column(vals[i::7]) for i in range(7)]
    merged = parts[0]
    for p in parts[1:]:
        merged = merge_column_summaries(merged, p)
    two = find_bin_from_summary(merged, 63)
    _mappers_equal([one], [two])


def test_summary_merge_categorical():
    rng = np.random.RandomState(2)
    vals = rng.randint(0, 40, 2000).astype(np.float64)
    one = find_bin(vals, max_bin=16, is_categorical=True)
    a = summarize_column(vals[:777], is_categorical=True)
    b = summarize_column(vals[777:], is_categorical=True)
    two = find_bin_from_summary(merge_column_summaries(a, b), 16)
    _mappers_equal([one], [two])


def test_sketch_serialize_roundtrip():
    X, _ = _data()
    sk = BinningSketch(X.shape[1], cat_indices=[4])
    sk.update(X[:1500])
    sk.update(X[1500:])
    flat, layout = sk.serialize()
    sk2 = BinningSketch.deserialize(flat, layout, cat_indices=[4])
    for j in range(X.shape[1]):
        a, b = sk.summary(j), sk2.summary(j)
        assert np.array_equal(a.distinct, b.distinct)
        assert np.array_equal(a.counts, b.counts)
        assert (a.na_cnt, a.total_cnt) == (b.na_cnt, b.total_cnt)


def test_sample_row_indices_matches_incore_draw():
    n, cnt, seed = 5000, 1200, 17
    rng = np.random.RandomState(seed)
    expect = np.sort(rng.choice(n, size=cnt, replace=False))
    assert np.array_equal(sample_row_indices(n, cnt, seed), expect)


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------

def test_chunk_rows_quantum_validated():
    with pytest.raises(ValueError, match="multiple"):
        ArraySource(np.zeros((10, 2)), chunk_rows=100)


def test_numpy_mmap_source(tmp_path):
    X, y = _data(1500, 4, seed=3)
    xp = tmp_path / "x.npy"
    yp = tmp_path / "y.npy"
    np.save(xp, X)
    np.save(yp, y)
    src = NumpyMmapSource(str(xp), str(yp), chunk_rows=512)
    assert src.num_rows() == 1500 and src.num_features() == 4
    got = np.concatenate([c.X for c in src.chunks()])
    lab = np.concatenate([c.label for c in src.chunks()])
    assert np.array_equal(np.nan_to_num(got), np.nan_to_num(X))
    assert np.array_equal(lab, y)


def test_csv_source(tmp_path):
    rng = np.random.RandomState(4)
    X = rng.randn(700, 3)
    y = (X[:, 0] > 0).astype(np.float64)
    path = tmp_path / "d.csv"
    with open(path, "w") as fh:
        for i in range(700):
            fh.write(",".join(f"{v:.9g}" for v in [y[i]] + list(X[i])) + "\n")
    src = CSVSource(str(path), chunk_rows=256)
    assert src.num_rows() == 700 and src.num_features() == 3
    got = np.concatenate([c.X for c in src.chunks()])
    lab = np.concatenate([c.label for c in src.chunks()])
    assert np.allclose(got, X, atol=1e-7)
    assert np.array_equal(lab, y)


def test_csv_source_comments_and_header(tmp_path):
    """Leading '#' comment lines and a header: num_rows() must agree
    with what chunks() yields (a mismatch crashes the spill memmap)."""
    rng = np.random.RandomState(8)
    X = rng.randn(300, 2)
    y = (X[:, 0] > 0).astype(np.float64)
    path = tmp_path / "c.csv"
    with open(path, "w") as fh:
        fh.write("# a comment before the header\n")
        fh.write("target,a,b\n")
        fh.write("# and one after\n")
        for i in range(300):
            fh.write(f"{y[i]:g},{X[i,0]:.9g},{X[i,1]:.9g}\n")
    src = CSVSource(str(path), params={"header": "true"}, chunk_rows=256)
    assert src.num_rows() == 300 and src.num_features() == 2
    assert src.feature_names() == ["a", "b"]
    got = np.concatenate([c.X for c in src.chunks()])
    assert got.shape == (300, 2)
    assert np.allclose(got, X, atol=1e-7)
    sd = StreamedDataset(src, params={"verbosity": -1}).construct()
    assert sd.num_data() == 300


def test_arrow_source(tmp_path):
    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")
    rng = np.random.RandomState(6)
    X = rng.randn(900, 3)
    y = (X[:, 0] > 0).astype(np.float64)
    tbl = pa.table({"f0": X[:, 0], "f1": X[:, 1], "f2": X[:, 2],
                    "target": y})
    path = str(tmp_path / "d.parquet")
    pq.write_table(tbl, path, row_group_size=256)
    from lightgbm_tpu.ingest import ArrowSource
    src = ArrowSource(path, label="target", chunk_rows=256)
    assert src.num_rows() == 900 and src.num_features() == 3
    assert src.feature_names() == ["f0", "f1", "f2"]
    got = np.concatenate([c.X for c in src.chunks()])
    lab = np.concatenate([c.label for c in src.chunks()])
    assert np.allclose(got, X)
    assert np.array_equal(lab, y)
    params = {"verbosity": -1, "enable_bundle": False}
    sd = StreamedDataset(src, params=params).construct()
    ds = lgb.Dataset(X.copy(), label=y.copy(), params=params).construct()
    assert np.array_equal(np.asarray(ds.X_binned), np.asarray(sd.X_binned))


def test_synthetic_source_reiterates_identically():
    src = SyntheticSource(2000, 5, chunk_rows=512, seed=9)
    a = [c.X.copy() for c in src.chunks()]
    b = [c.X.copy() for c in src.chunks()]
    for xa, xb in zip(a, b):
        assert np.array_equal(xa, xb)
    assert sum(len(x) for x in a) == 2000


# ---------------------------------------------------------------------------
# StreamedDataset: construct identity with in-core
# ---------------------------------------------------------------------------

def test_streamed_dataset_matches_incore():
    X, y = _data()
    params = {"verbosity": -1, "enable_bundle": False,
              "bin_construct_sample_cnt": 1200}
    ds = lgb.Dataset(X.copy(), label=y.copy(), params=params,
                     categorical_feature=[4]).construct()
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=512), params=params,
                         categorical_feature=[4]).construct()
    _mappers_equal(ds.bin_mappers, sd.bin_mappers)
    assert np.array_equal(ds.used_feature_map, sd.used_feature_map)
    assert np.array_equal(np.asarray(ds.X_binned), np.asarray(sd.X_binned))
    assert ds.fingerprint() == sd.fingerprint()
    assert np.array_equal(ds.metadata.label, sd.metadata.label)


@pytest.mark.parametrize("n", [2048, 2049])
def test_streamed_dataset_chunk_boundaries(n):
    X, y = _data(n, 5, seed=11)
    params = {"verbosity": -1, "enable_bundle": False}
    ds = lgb.Dataset(X.copy(), label=y.copy(), params=params).construct()
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=512),
                         params=params).construct()
    assert np.array_equal(np.asarray(ds.X_binned), np.asarray(sd.X_binned))
    assert ds.fingerprint() == sd.fingerprint()


def test_streamed_dataset_spill_is_on_disk(tmp_path):
    X, y = _data(2048, 4, seed=5)
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=512),
                         params={"verbosity": -1},
                         spill_dir=str(tmp_path)).construct()
    assert isinstance(sd.X_binned, np.memmap)
    assert os.path.getsize(os.path.join(str(tmp_path), "binned.dat")) == \
        sd.X_binned.shape[0] * sd.X_binned.shape[1]
    # caller-provided spill dirs survive close() (reusable caches)...
    sd.close()
    assert os.path.exists(os.path.join(str(tmp_path), "binned.dat"))
    # ...self-created temp spills are deleted (no /tmp accumulation
    # across CV sweeps / bench ladders)
    sd2 = StreamedDataset(ArraySource(X, y, chunk_rows=512),
                          params={"verbosity": -1}).construct()
    own = sd2.spill_dir
    assert own and os.path.exists(own)
    sd2.close()
    assert not os.path.exists(own)


# ---------------------------------------------------------------------------
# engine.train (hbm route): streamed-vs-in-core bit-identity matrix
# ---------------------------------------------------------------------------

_BASE = {"objective": "binary", "verbosity": -1, "num_leaves": 13,
         "learning_rate": 0.2, "max_bin": 63, "min_data_in_leaf": 5,
         "enable_bundle": False, "seed": 3}


@pytest.mark.parametrize("name,extra", [
    ("serial", {}),
    ("wave", {"tree_grow_mode": "wave", "tpu_wave_size": 4}),
    ("quantized", {"tree_grow_mode": "wave", "use_quantized_grad": True}),
    ("dp_scatter", {"tree_learner": "data", "num_machines": 8,
                    "num_devices": 8, "use_quantized_grad": True,
                    "tpu_dp_hist_scatter": True}),
])
def test_hbm_route_bit_identity(name, extra):
    import jax
    if name == "dp_scatter" and jax.device_count() < 8:
        pytest.skip("needs 8 (virtual) devices")
    X, y = _data(3001, 6, seed=7)
    X = np.nan_to_num(X)
    p = dict(_BASE)
    p.update(extra)
    ds = lgb.Dataset(X.copy(), label=y.copy())
    t1 = lgb.train(p, ds, num_boost_round=5).model_to_string()
    sd = StreamedDataset(ArraySource(X, y, chunk_rows=512), params=p)
    bst2 = lgb.train(p, sd, num_boost_round=5)
    assert t1 == bst2.model_to_string(), \
        f"streamed {name} training diverged from in-core"


# ---------------------------------------------------------------------------
# memory budget: no rows term
# ---------------------------------------------------------------------------

def test_ingest_memory_budget_flat_in_rows():
    from lightgbm_tpu.analysis.contracts import memory_budget_for, \
        resolve_limit
    from lightgbm_tpu.ingest import stream as _stream  # noqa: F401
    b = memory_budget_for("ingest")
    assert b is not None and b.name == "ingest/chunk_pipeline"
    ctx = {"features": 28, "bins": 255, "wave_size": 25, "leaves": 255,
           "chunk_rows": 1 << 20, "itemsize": 4, "quantized": True}
    small = resolve_limit(b.hbm_per_device, dict(ctx, rows=10 ** 3))
    huge = resolve_limit(b.hbm_per_device, dict(ctx, rows=10 ** 12))
    assert small == huge, "ingest budget must not depend on total rows"
    # but it must scale with the chunk budget
    bigger = resolve_limit(b.hbm_per_device,
                           dict(ctx, chunk_rows=1 << 24, rows=10 ** 3))
    assert bigger > small


def test_ingest_lint_config_clean():
    from lightgbm_tpu.analysis.lint import build_unit
    from lightgbm_tpu.analysis.rules import run_rules, DEFAULT_RULES
    unit = build_unit("ingest")
    assert unit.jaxpr is not None
    assert not unit.collectives
    vs = run_rules([unit], rules=DEFAULT_RULES)
    assert not vs, [v.to_json() for v in vs]
