"""Direct tests for the host-side allgather behind pre-partitioned
ingest (``distributed.allgather_host``) and the mergeable-sketch wire
format that rides it (ISSUE 18 satellites).

A 2-rank world is SIMULATED: ``multihost_utils.process_allgather`` is
replaced with a fake that answers each rank's calls from the full set of
per-rank operands (the transform allgather_host applies to its operand —
length probe, then max-pad — is reproduced per rank), so the collective's
padding/trim/rank-order logic runs exactly as in a real 2-process gloo
run, in one process.  Covered:

  * float64 bit-exactness — x64 is off in JAX, so f64 payloads ship as
    uint32 bit-pairs; NaN payloads, -0.0, denormals and full-precision
    pi must survive BIT-identically (bin boundaries and labels ride
    this);
  * variable / empty per-rank lengths — the max-pad + trim must
    reassemble exactly, including a rank contributing zero rows;
  * rank-order preservation — the concatenation is rank-major;
  * single-process passthrough — no collective, the input comes back;
  * sketch.allgather_merge — two half-data sketches merged over the
    simulated wire finalize into the SAME BinMappers as one sketch over
    the full matrix (the distributed-binning bit-identity root).
"""

import numpy as np
import pytest

import jax

from lightgbm_tpu import distributed as dist
from lightgbm_tpu.ingest.sketch import BinningSketch


class _FakeWorld:
    """Answers ``process_allgather`` for a simulated rank set.

    allgather_host issues exactly two collectives per (non-f64) call —
    the int32 length probe, then the max-padded payload — so the fake
    alternates: even calls return every rank's length, odd calls every
    rank's padded operand.  ``rank_inputs`` holds each rank's operand in
    the SAME form allgather_host would send (f64 callers recurse through
    the uint32 view before gathering, so f64 world inputs are viewed
    here too)."""

    def __init__(self, rank_inputs):
        self.rank_inputs = [
            np.asarray(a).view(np.uint32) if np.asarray(a).dtype ==
            np.float64 else np.asarray(a) for a in rank_inputs]
        self.calls = 0

    def __call__(self, x):
        i, self.calls = self.calls, self.calls + 1
        if i % 2 == 0:      # length probe
            return np.stack([np.asarray([a.shape[0]], np.int32)
                             for a in self.rank_inputs])
        m = max(a.shape[0] for a in self.rank_inputs)

        def pad(a):
            if m > a.shape[0]:
                z = np.zeros((m - a.shape[0],) + a.shape[1:], a.dtype)
                return np.concatenate([a, z], axis=0)
            return a

        return np.stack([pad(a) for a in self.rank_inputs])


def _gather_as_rank(rank_inputs, rank=0, monkeypatch=None):
    """Run rank ``rank``'s allgather_host against the simulated world."""
    world = _FakeWorld(rank_inputs)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr("jax.experimental.multihost_utils.process_allgather",
                        world)
    return dist.allgather_host(np.asarray(rank_inputs[rank]))


def test_float64_bits_survive_the_uint32_roundtrip(monkeypatch):
    """NaN payload bits, -0.0, a denormal and full-precision pi must
    come back BIT-identical (f64 would silently round to f32 in transit
    with x64 off; the uint32 view is the wire format)."""
    a0 = np.array([np.pi, -0.0, 5e-324, 1.0 + 2 ** -52], np.float64)
    a1 = np.array([np.nan, -np.inf, 1e308], np.float64)
    got = _gather_as_rank([a0, a1], monkeypatch=monkeypatch)
    want = np.concatenate([a0, a1])
    assert got.dtype == np.float64
    np.testing.assert_array_equal(got.view(np.uint64), want.view(np.uint64))


def test_empty_rank_arrays(monkeypatch):
    """A rank contributing zero rows must vanish from the result (and
    an all-empty world must produce an empty array, not an error)."""
    a0 = np.arange(6, dtype=np.int32)
    a1 = np.zeros((0,), np.int32)
    got = _gather_as_rank([a0, a1], monkeypatch=monkeypatch)
    np.testing.assert_array_equal(got, a0)
    got2 = _gather_as_rank([a1, a0], monkeypatch=monkeypatch)
    np.testing.assert_array_equal(got2, a0)
    got3 = _gather_as_rank([a1, a1.copy()], monkeypatch=monkeypatch)
    assert got3.shape == (0,)


def test_rank_order_and_variable_lengths(monkeypatch):
    """Rank-major concatenation with unequal lengths (max-pad + trim):
    no pad value may leak and order is rank 0 then rank 1."""
    a0 = np.full((3, 2), 7, np.int32)
    a1 = np.full((5, 2), 9, np.int32)
    got = _gather_as_rank([a0, a1], monkeypatch=monkeypatch)
    np.testing.assert_array_equal(got, np.concatenate([a0, a1]))


def test_single_process_passthrough():
    """process_count()==1: the input comes back unchanged, no collective
    touched (a real multihost_utils call here would require a
    distributed client)."""
    a = np.array([1.5, np.nan, -0.0], np.float64)
    got = dist.allgather_host(a)
    np.testing.assert_array_equal(np.asarray(got).view(np.uint64),
                                  a.view(np.uint64))


def test_sketch_allgather_merge_matches_in_core(monkeypatch):
    """Two ranks each sketch HALF the rows; after allgather_merge over
    the simulated wire both finalize the SAME BinMappers as one sketch
    over all rows — the distributed-binning parity contract
    (dataset_loader.cpp:1040-1130's BinMapper allgather at summary
    granularity)."""
    rng = np.random.RandomState(0)
    rows = rng.randn(400, 5)
    rows[rng.rand(400) < 0.1, 2] = np.nan
    rows[:, 4] = rng.randint(0, 6, 400)          # categorical-ish
    half = [rows[:200], rows[200:]]

    sketches = []
    for part in half:
        sk = BinningSketch(5, cat_indices=[4])
        sk.update(part)
        sketches.append(sk)
    payloads = [sk.serialize() for sk in sketches]

    calls = {"n": 0}

    def fake_allgather(arr):
        # allgather_merge's fixed call sequence: sizes, flats, layouts
        i, calls["n"] = calls["n"], calls["n"] + 1
        if i % 3 == 0:
            return np.asarray([[len(p[0])] for p in payloads],
                              np.float64).ravel()
        if i % 3 == 1:
            return np.concatenate([p[0] for p in payloads])
        return np.concatenate([p[1].astype(np.float64).reshape(-1)
                               for p in payloads])

    monkeypatch.setattr(dist, "is_initialized", lambda: True)
    monkeypatch.setattr(dist, "process_count", lambda: 2)
    monkeypatch.setattr(dist, "allgather_host", fake_allgather)

    merged = sketches[0].allgather_merge()
    assert merged.rows_seen == 400

    full = BinningSketch(5, cat_indices=[4])
    full.update(rows)
    kw = dict(max_bin=63, min_data_in_bin=3)
    got = merged.finalize(**kw)
    want = full.finalize(**kw)
    for j, (g, w) in enumerate(zip(got, want)):
        assert g.num_bin == w.num_bin, j
        assert g.is_categorical == w.is_categorical, j
        np.testing.assert_array_equal(
            np.asarray(g.bin_upper_bound, np.float64),
            np.asarray(w.bin_upper_bound, np.float64), err_msg=f"f{j}")
