"""sklearn API tests (analog of reference test_sklearn.py)."""

import numpy as np

from lightgbm_tpu import LGBMClassifier, LGBMRanker, LGBMRegressor

KW = dict(num_leaves=7, min_child_samples=5, n_estimators=10)


def test_regressor(regression_data):
    X, y = regression_data
    m = LGBMRegressor(**KW).fit(X, y)
    p = m.predict(X)
    assert np.mean((p - y) ** 2) < 0.5 * np.var(y)
    assert m.n_features_ == X.shape[1]
    assert m.feature_importances_.shape == (X.shape[1],)


def test_classifier_binary(binary_data):
    X, y = binary_data
    m = LGBMClassifier(**KW).fit(X, y)
    pred = m.predict(X)
    assert set(np.unique(pred)) <= set(np.unique(y))
    assert (pred == y).mean() > 0.9
    proba = m.predict_proba(X)
    assert proba.shape == (len(y), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)


def test_classifier_multiclass(multiclass_data):
    X, y = multiclass_data
    m = LGBMClassifier(**KW).fit(X, y)
    assert m.n_classes_ == 3
    proba = m.predict_proba(X)
    assert proba.shape == (len(y), 3)
    assert (m.predict(X) == y).mean() > 0.85


def test_classifier_string_labels(binary_data):
    X, y = binary_data
    ys = np.where(y > 0, "pos", "neg")
    m = LGBMClassifier(**KW).fit(X, ys)
    pred = m.predict(X)
    assert set(np.unique(pred)) <= {"pos", "neg"}
    assert (pred == ys).mean() > 0.9


def test_ranker(rank_data):
    X, y, group = rank_data
    m = LGBMRanker(**KW, learning_rate=0.2).fit(X, y, group=group)
    p = m.predict(X)
    assert np.corrcoef(p, y)[0, 1] > 0.4


def test_eval_set_early_stopping():
    rng = np.random.RandomState(0)
    X = rng.randn(200, 5)
    y = X[:, 0] + 1.5 * rng.randn(200)
    m = LGBMRegressor(**dict(KW, n_estimators=100, learning_rate=0.5,
                             min_child_samples=2))
    m.fit(X[:120], y[:120], eval_set=[(X[120:], y[120:])], eval_metric="l2",
          early_stopping_rounds=5)
    assert 0 < m.best_iteration_ < 100
    assert "valid_0" in m.evals_result_


def test_get_set_params():
    m = LGBMRegressor(num_leaves=15, learning_rate=0.2)
    p = m.get_params()
    assert p["num_leaves"] == 15
    m.set_params(num_leaves=31)
    assert m.num_leaves == 31


def test_custom_objective(regression_data):
    X, y = regression_data

    def l2_obj(y_true, y_pred):
        return y_pred - y_true, np.ones_like(y_pred)

    m = LGBMRegressor(**KW, objective=l2_obj).fit(X, y)
    p = m.predict(X, raw_score=True)
    assert np.mean((p - y) ** 2) < 0.6 * np.var(y)
