"""Serving-layer tests (lightgbm_tpu.serve): bucketed predictor parity
with Booster.predict across bucket boundaries, micro-batcher correctness
under concurrent submitters, registry hot-swap atomicity, and end-to-end
HTTP smoke tests over localhost (slow-marked)."""

import json
import os
import sys
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve import (MicroBatcher, ModelRegistry,
                                PredictionServer)

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


@pytest.fixture(scope="module")
def booster(binary_data):
    X, y = binary_data
    p = {**SMALL, "objective": "binary"}
    return lgb.train(p, lgb.Dataset(X, y, params=p), 15)


@pytest.fixture(scope="module")
def predictor(booster):
    return booster.to_predictor(warmup=True)


# -- shape buckets ----------------------------------------------------------
def test_bucket_ladder():
    from lightgbm_tpu.models.tree import bucket_rows
    assert [bucket_rows(n) for n in (0, 1, 2, 8, 9, 64, 65, 512, 513,
                                     4096, 4097, 10000)] == \
        [1, 1, 8, 8, 64, 64, 512, 512, 4096, 4096, 8192, 12288]


@pytest.mark.parametrize("n", [1, 7, 8, 9, 511, 513])
def test_bucket_parity(n, booster, predictor):
    """Bucketed predictor output is bitwise identical to Booster.predict
    across bucket boundaries."""
    rng = np.random.RandomState(n)
    Xs = rng.randn(n, 6)
    assert np.array_equal(predictor.predict(Xs), booster.predict(Xs))
    assert np.array_equal(predictor.predict(Xs, raw_score=True),
                          booster.predict(Xs, raw_score=True))


def test_zero_recompiles_after_warmup(predictor):
    r0 = predictor.stats.snapshot()["recompiles"]
    assert r0 >= 0
    rng = np.random.RandomState(0)
    for n in (1, 2, 3, 5, 9, 63, 65, 511, 513, 4096):
        predictor.predict(rng.randn(n, predictor.num_features))
    assert predictor.stats.snapshot()["recompiles"] == r0


def test_predictor_nan_and_single_row(booster, predictor):
    rng = np.random.RandomState(1)
    Xs = rng.randn(5, 6)
    Xs[2, 1] = np.nan
    assert np.array_equal(predictor.predict(Xs), booster.predict(Xs))
    # 1-D row is accepted as one request row
    assert np.array_equal(predictor.predict(Xs[0]),
                          booster.predict(Xs[0].reshape(1, -1)))


def test_multiclass_predictor_parity(multiclass_data):
    X, y = multiclass_data
    p = {**SMALL, "objective": "multiclass", "num_class": 3}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 8)
    pred = bst.to_predictor(warmup=True)
    rng = np.random.RandomState(3)
    for n in (1, 9, 130):
        Xs = rng.randn(n, 6)
        out = pred.predict(Xs)
        assert out.shape == (n, 3)
        assert np.array_equal(out, bst.predict(Xs))


def test_categorical_predictor_parity():
    """Categorical models take the sequential walk kind — parity must
    hold there too."""
    rng = np.random.RandomState(5)
    n = 600
    Xc = rng.randn(n, 6)
    Xc[:, 3] = rng.randint(0, 12, n)
    # the label hangs mostly on the CATEGORY so the trees must split on it
    y = ((Xc[:, 3] % 3 == 0) * 2.0 + 0.3 * Xc[:, 0] +
         0.3 * rng.randn(n) > 1.0).astype(np.float64)
    p = {**SMALL, "objective": "binary"}
    ds = lgb.Dataset(Xc, y, categorical_feature=[3], params=p)
    bst = lgb.train(p, ds, 10)
    pred = bst.to_predictor()
    info = pred.info()
    # the inference compiler routes categorical ensembles too (the
    # bitset-membership contraction) — and when it decides the walk it
    # must say why, never silently
    assert info["compiler"] in ("dense", "walk")
    if info["compiler"] == "dense":
        assert info["dense"]["has_cat"]
    else:
        assert info["fallback_reason"]
    Xq = rng.randn(9, 6)
    Xq[:, 3] = rng.randint(0, 14, 9)  # incl. unseen category 12/13
    assert np.array_equal(pred.predict(Xq), bst.predict(Xq))
    # the forced-walk path stays available and bitwise-consistent with
    # the sequential kernels
    pw = bst.to_predictor(compiler="walk")
    assert pw.info()["compiler"] == "walk"
    assert pw.info()["fallback_reason"] == "forced_walk"
    assert "seq" in pw.info()["kinds"]
    assert np.allclose(pw.predict(Xq), pred.predict(Xq), rtol=1e-6,
                       atol=1e-7)


def test_linear_tree_predictor_parity(regression_data):
    X, y = regression_data
    p = {**SMALL, "objective": "regression", "linear_tree": True}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 8)
    pred = bst.to_predictor()
    info = pred.info()
    if info["compiler"] == "dense":
        assert info["dense"]["has_linear"]
    else:
        assert info["kinds"] == ["dense_lin"]
    rng = np.random.RandomState(6)
    Xq = rng.randn(9, 6)
    Xq[3, 0] = np.nan  # linear leaves fall back to plain output on NaN
    assert np.array_equal(pred.predict(Xq), bst.predict(Xq))


def test_rf_predictor_parity(binary_data):
    """RF models predict the MEAN of tree outputs; the predictor must
    apply the same averaging."""
    X, y = binary_data
    p = {**SMALL, "objective": "binary", "boosting": "rf",
         "bagging_freq": 1, "bagging_fraction": 0.8}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 6)
    pred = bst.to_predictor()
    rng = np.random.RandomState(8)
    Xq = rng.randn(9, 6)
    assert np.array_equal(pred.predict(Xq), bst.predict(Xq))


def test_stats_counters(booster):
    pred = booster.to_predictor()
    pred.predict(np.zeros((3, 6), np.float32))
    pred.predict(np.zeros((70, 6), np.float32))
    s = pred.stats.snapshot()
    assert s["batches"] == 2 and s["rows"] == 73
    assert s["bucket_histogram"] == {"8": 1, "512": 1}
    assert s["latency_ms"]["p50"] > 0


# -- micro-batcher ----------------------------------------------------------
def test_batcher_concurrent_submitters(booster, predictor):
    rng = np.random.RandomState(7)
    inputs = [rng.randn(1 + (i * 13) % 40, 6) for i in range(24)]
    refs = [booster.predict(Xs) for Xs in inputs]
    mb = MicroBatcher(lambda X, raw: predictor.predict(X, raw_score=raw),
                      max_wait_ms=5.0)
    try:
        outs = [None] * len(inputs)

        def worker(lo, hi):
            for i in range(lo, hi):
                outs[i] = mb.predict(inputs[i])

        threads = [threading.Thread(target=worker, args=(i * 3, i * 3 + 3))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for out, ref in zip(outs, refs):
            assert np.array_equal(out, ref)
    finally:
        mb.close()


def test_batcher_bad_request_does_not_poison_batch(predictor):
    mb = MicroBatcher(lambda X, raw: predictor.predict(X, raw_score=raw),
                      max_wait_ms=20.0)
    try:
        good = mb.submit(np.zeros((2, 6), np.float32))
        bad = mb.submit(np.zeros((2, 9), np.float32))  # wrong width
        assert good.result(timeout=30).shape == (2,)
        with pytest.raises(Exception):
            bad.result(timeout=30)
    finally:
        mb.close()


# -- registry ---------------------------------------------------------------
def test_registry_basics(booster):
    reg = ModelRegistry()
    with pytest.raises(KeyError):
        reg.get()
    reg.load("a", booster, warmup=False)
    assert reg.get() is reg.get("a")  # single model needs no name
    reg.load("b", booster, warmup=False)
    with pytest.raises(KeyError):
        reg.get()  # ambiguous now
    info = reg.info()
    assert set(info) == {"a", "b"} and info["a"]["version"] == 1
    assert reg.evict("a") and not reg.evict("a")
    assert reg.names() == ["b"]


def test_registry_hot_swap_atomic(binary_data):
    """Readers racing a rollout must see exactly one version's output,
    never a mix."""
    X, y = binary_data
    p = {**SMALL, "objective": "binary"}
    b1 = lgb.train(p, lgb.Dataset(X, y, params=p), 5)
    b2 = lgb.train(p, lgb.Dataset(X, y, params=p), 9)
    rng = np.random.RandomState(11)
    Xq = rng.randn(9, 6)
    ref1, ref2 = b1.predict(Xq), b2.predict(Xq)
    assert not np.array_equal(ref1, ref2)
    reg = ModelRegistry()
    reg.load("m", b1, warmup=False)
    bad = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            out = reg.get("m").predict(Xq)
            if not (np.array_equal(out, ref1) or np.array_equal(out, ref2)):
                bad.append(out)

    readers = [threading.Thread(target=reader) for _ in range(4)]
    for t in readers:
        t.start()
    for i in range(6):
        reg.load("m", b2 if i % 2 == 0 else b1, warmup=False)
    stop.set()
    for t in readers:
        t.join()
    assert not bad, "hot-swap produced mixed-version outputs"
    assert reg.info()["m"]["version"] == 7


def test_registry_hot_swap_dense_atomic(binary_data):
    """Hot-swapping a DENSE-compiled model must rebuild the whole
    compiled program atomically: readers racing the rollout see exactly
    one version's output (path matrices and leaf tables can never come
    from different versions), and stats carry over the swap."""
    X, y = binary_data
    p = {**SMALL, "objective": "binary"}
    b1 = lgb.train(p, lgb.Dataset(X, y, params=p), 5)
    b2 = lgb.train(p, lgb.Dataset(X, y, params=p), 9)
    rng = np.random.RandomState(12)
    Xq = rng.randn(9, 6)
    reg = ModelRegistry()
    reg.load("m", b1, warmup=False, compiler="dense")
    assert reg.get("m").info()["compiler"] == "dense"
    ref1 = reg.get("m").predict(Xq)
    reg.load("m", b2, warmup=False, compiler="dense")
    ref2 = reg.get("m").predict(Xq)
    assert not np.array_equal(ref1, ref2)
    reg.get("m").predict(Xq)
    batches_before = reg.stats()["m"]["batches"]
    bad = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            out = reg.get("m").predict(Xq)
            if not (np.array_equal(out, ref1) or np.array_equal(out, ref2)):
                bad.append(out)

    readers = [threading.Thread(target=reader) for _ in range(4)]
    for t in readers:
        t.start()
    for i in range(6):
        reg.load("m", b1 if i % 2 == 0 else b2, warmup=False,
                 compiler="dense")
    stop.set()
    for t in readers:
        t.join()
    assert not bad, "dense hot-swap produced mixed-version outputs"
    # stats survive the swaps (the counters track the NAME, and the new
    # executable was fully built before the one-assignment swap)
    assert reg.stats()["m"]["batches"] > batches_before
    assert reg.info()["m"]["version"] == 8
    assert reg.info()["m"]["compiler"] == "dense"


def test_registry_swap_keeps_stats(booster):
    reg = ModelRegistry()
    reg.load("m", booster, warmup=False)
    reg.get("m").predict(np.zeros((2, 6), np.float32))
    before = reg.stats()["m"]["batches"]
    reg.load("m", booster, warmup=False)  # hot-swap, stats carry over
    assert reg.stats()["m"]["batches"] == before


def test_registry_load_failure_mid_hot_swap_leaves_old_serving(
        tmp_path, binary_data, booster):
    """A corrupt source mid-hot-swap surfaces the typed error and
    leaves the OLD predictor serving untouched — same version, same
    stats, never a torn or evicted entry."""
    from lightgbm_tpu.models.model_text import ModelCorruptError
    X, _ = binary_data
    good = str(tmp_path / "good.txt")
    booster.save_model(good)
    corrupt = str(tmp_path / "corrupt.txt")
    with open(good) as fh:
        text = fh.read()
    with open(corrupt, "w") as fh:
        fh.write(text[: len(text) // 3])        # truncated mid-field
    reg = ModelRegistry()
    reg.load("m", good, warmup=False)
    ref = reg.get("m").predict(X[:5])
    reg.get("m").predict(X[:5])
    batches_before = reg.stats()["m"]["batches"]
    with pytest.raises(ModelCorruptError):
        reg.load("m", corrupt, warmup=False)
    # old version intact: same predictions, same version, same source,
    # stats still accumulating on the same series
    assert np.array_equal(reg.get("m").predict(X[:5]), ref)
    info = reg.info()["m"]
    assert info["version"] == 1 and info["source"] == good
    reg.get("m").predict(X[:5])
    # two predicts since the failed swap (the parity check + this one)
    # landed on the SAME stats series — nothing was torn or reset
    assert reg.stats()["m"]["batches"] == batches_before + 2
    # a failed FIRST load leaves no phantom entry behind
    reg2 = ModelRegistry()
    with pytest.raises(ModelCorruptError):
        reg2.load("x", corrupt, warmup=False)
    assert reg2.names() == [] and reg2.stats() == {}
    reg2.load("x", good, warmup=False)          # name still usable
    assert reg2.info()["x"]["version"] == 1


def test_shutdown_drain_exactly_one_terminal_response(tmp_path,
                                                      binary_data,
                                                      booster):
    """Satellite: a queued request racing PredictionServer.shutdown()
    gets exactly one terminal response — a result, or a typed 5xx from
    the ServerClosed/draining path — never a hung future."""
    import http.client
    X, _ = binary_data
    reg = _slow_registry(tmp_path, booster, delay=0.15)
    srv = PredictionServer(reg, port=0, max_wait_ms=0.5,
                           max_batch_rows=1).start()
    row = X[0].tolist()
    results = []
    lock = threading.Lock()

    def hit():
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=60)
        try:
            out = _post(conn, "/predict", {"rows": [row]})
        except Exception as exc:      # severed mid-drain: terminal too
            out = ("conn_error", type(exc).__name__)
        with lock:
            results.append(out)
        conn.close()

    threads = [threading.Thread(target=hit) for _ in range(8)]
    for t in threads:
        t.start()
    import time
    time.sleep(0.2)            # some requests queued, one on device
    srv.shutdown()
    for t in threads:
        t.join(30)
    assert not any(t.is_alive() for t in threads), \
        "a request hung through shutdown"
    assert len(results) == 8   # every request got a terminal outcome
    statuses = [r[0] for r in results]
    assert all(s in (200, 503, 504, "conn_error") for s in statuses), \
        statuses
    assert statuses.count(200) >= 1  # in-flight work completed


# -- end-to-end HTTP --------------------------------------------------------
def _post(conn, path, payload):
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    return resp.status, json.loads(resp.read())


def _get(conn, path):
    conn.request("GET", path)
    resp = conn.getresponse()
    return resp.status, json.loads(resp.read())


@pytest.mark.slow
def test_serve_e2e_http(tmp_path, binary_data, booster):
    """The acceptance flow: a warm server answers 1000 sequential
    single-row /predict requests with ZERO recompiles after warmup,
    verified through the /stats counter; plus /healthz, /models listing,
    and an over-HTTP hot-swap."""
    import http.client
    X, y = binary_data
    model_file = str(tmp_path / "model.txt")
    booster.save_model(model_file)
    reg = ModelRegistry()
    reg.load("model", model_file, warmup=True)
    srv = PredictionServer(reg, port=0, max_wait_ms=0.5).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
        status, health = _get(conn, "/healthz")
        assert status == 200 and health["models"] == ["model"]
        status, models = _get(conn, "/models")
        assert status == 200 and models["model"]["num_trees"] == 15
        recompiles0 = _get(conn, "/stats")[1]["model"]["recompiles"]

        row = X[0].tolist()
        ref = float(booster.predict(X[:1])[0])
        for _ in range(1000):
            status, body = _post(conn, "/predict", {"rows": [row]})
            assert status == 200
            assert body["predictions"][0] == pytest.approx(ref, abs=0.0)
        status, stats = _get(conn, "/stats")
        assert stats["model"]["recompiles"] == recompiles0, \
            "single-row traffic recompiled after warmup"
        assert stats["model"]["requests"] >= 1000
        assert stats["model"]["bucket_histogram"].get("1", 0) >= 1000

        # error paths
        assert _post(conn, "/predict", {})[0] == 400
        assert _post(conn, "/predict", {"rows": [row],
                                        "model": "nope"})[0] == 404
        assert _get(conn, "/bogus")[0] == 404

        # hot-swap over HTTP: predictions switch to the new version
        p = {**SMALL, "objective": "binary"}
        b2 = lgb.train(p, lgb.Dataset(X, y, params=p), 7)
        model2 = str(tmp_path / "model2.txt")
        b2.save_model(model2)
        status, info = _post(conn, "/models", {"name": "model",
                                               "file": model2})
        assert status == 200 and info["num_trees"] == 7
        _, body = _post(conn, "/predict", {"row": row})
        assert body["predictions"][0] == pytest.approx(
            float(b2.predict(X[:1])[0]), abs=0.0)
    finally:
        srv.shutdown()


@pytest.mark.slow
def test_serve_cli_subprocess(tmp_path, booster, binary_data):
    """`python -m lightgbm_tpu serve model.txt` boots, answers /predict,
    and dies cleanly."""
    import http.client
    import re
    import subprocess
    import time
    X, _ = binary_data
    model_file = str(tmp_path / "model.txt")
    booster.save_model(model_file)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo,
           "PYTHONUNBUFFERED": "1"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "serve", model_file,
         "port=0", "warmup=0"],
        cwd=repo, env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line and proc.poll() is not None:
                break
            m = re.search(r"listening on http://[^:]+:(\d+)", line)
            if m:
                port = int(m.group(1))
                break
        assert port, "server never reported its port"
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        status, body = _post(conn, "/predict", {"row": X[0].tolist()})
        assert status == 200
        assert body["predictions"][0] == pytest.approx(
            float(booster.predict(X[:1])[0]), abs=1e-12)
    finally:
        proc.terminate()
        proc.wait(timeout=30)


# -- admission control / degradation (resilience subsystem) -----------------
def _post_full(conn, path, payload):
    """Like _post but also returns the response headers."""
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    return resp.status, json.loads(resp.read()), dict(resp.getheaders())


def _slow_registry(tmp_path, booster, delay):
    import time
    model_file = str(tmp_path / "model.txt")
    booster.save_model(model_file)
    reg = ModelRegistry()
    reg.load("model", model_file, warmup=True)
    pred = reg.get("model")
    orig = pred.predict

    def slow_predict(X, raw_score=False, request_ids=()):
        # keep the real predict's signature: the batcher propagates
        # request_ids into predictors that accept them (PR 14), and a
        # patched predict without the kwarg turns every batch into a
        # TypeError 400
        time.sleep(delay)
        return orig(X, raw_score=raw_score, request_ids=request_ids)
    pred.predict = slow_predict
    return reg


@pytest.mark.slow
@pytest.mark.chaos
def test_serve_load_shed_503_and_degraded_healthz(tmp_path, binary_data,
                                                  booster):
    """Synthetic overload: a slow model + a 4-row queue bound. Admitted
    requests succeed, over-limit requests are shed with 503 +
    Retry-After, and /healthz flips to degraded while shedding."""
    import http.client
    X, _ = binary_data
    reg = _slow_registry(tmp_path, booster, delay=0.4)
    srv = PredictionServer(reg, port=0, max_wait_ms=0.5, max_batch_rows=4,
                           max_queue_rows=4).start()
    try:
        row = X[0].tolist()
        results = []
        lock = threading.Lock()

        def hit():
            conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                              timeout=60)
            out = _post_full(conn, "/predict", {"rows": [row]})
            with lock:
                results.append(out)
            conn.close()

        threads = [threading.Thread(target=hit) for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        statuses = [r[0] for r in results]
        assert statuses.count(200) >= 1, statuses
        assert statuses.count(503) >= 1, statuses
        shed = next(r for r in results if r[0] == 503)
        assert "queue is full" in shed[1]["error"]
        assert int(shed[2]["Retry-After"]) >= 1
        # degraded while sheds are recent — still HTTP 200
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
        status, health = _get(conn, "/healthz")
        assert status == 200
        assert health["status"] == "degraded"
        assert any("shedding" in r for r in health["reasons"])
    finally:
        srv.shutdown()


@pytest.mark.slow
@pytest.mark.chaos
def test_serve_deadline_504(tmp_path, binary_data, booster):
    """A request whose deadline elapses while the device is busy gets
    504 instead of hanging its handler thread; an unhurried request on
    the same server still succeeds."""
    import http.client
    X, _ = binary_data
    reg = _slow_registry(tmp_path, booster, delay=0.5)
    srv = PredictionServer(reg, port=0, max_wait_ms=0.5,
                           max_batch_rows=1).start()
    try:
        row = X[0].tolist()
        occupier = threading.Thread(target=lambda: _post(
            http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60),
            "/predict", {"rows": [row]}))
        occupier.start()
        import time
        time.sleep(0.15)  # the occupier's batch is now on the device
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
        status, body, _ = _post_full(conn, "/predict",
                                     {"rows": [row], "deadline_ms": 100})
        assert status == 504, body
        occupier.join(60)
        status, body = _post(conn, "/predict", {"rows": [row]})
        assert status == 200
    finally:
        srv.shutdown()


@pytest.mark.slow
@pytest.mark.chaos
def test_serve_sigterm_drains_and_exits_128_plus_signum(tmp_path,
                                                        booster,
                                                        binary_data):
    """Satellite: the serve CLI handles SIGTERM like training's
    PreemptionGuard — stop accepting, drain, exit 128+15 — and
    announces its port through port_file (the fleet supervisor's
    discovery channel)."""
    import http.client
    import signal as _signal
    import subprocess
    import time
    X, _ = binary_data
    model_file = str(tmp_path / "model.txt")
    booster.save_model(model_file)
    port_file = str(tmp_path / "serve.port")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo}
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu", "serve", model_file,
         "port=0", "warmup=0", f"port_file={port_file}"],
        cwd=repo, env=env, stdout=subprocess.DEVNULL,
        stderr=subprocess.STDOUT)
    try:
        deadline = time.monotonic() + 120
        port = None
        while time.monotonic() < deadline and port is None:
            try:
                with open(port_file) as fh:
                    port = int(fh.read().strip())
            except (OSError, ValueError):
                time.sleep(0.05)
        assert port, "port_file never appeared"
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        status, body = _post(conn, "/predict", {"row": X[0].tolist()})
        assert status == 200
        conn.close()
        proc.send_signal(_signal.SIGTERM)
        rc = proc.wait(timeout=60)
        assert rc == 128 + 15, f"exit code {rc}"
        # the socket is gone: a late request is refused, not hung
        with pytest.raises(OSError):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=5)
            conn.request("GET", "/healthz")
            conn.getresponse()
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(30)


def test_healthz_degraded_on_cpu_fallback(tmp_path, booster, monkeypatch):
    """/healthz reports degraded (with the probe's reason) while the
    process serves on the CPU fallback backend."""
    from lightgbm_tpu.utils import backend
    model_file = str(tmp_path / "model.txt")
    booster.save_model(model_file)
    reg = ModelRegistry()
    reg.load("model", model_file, warmup=False)
    srv = PredictionServer(reg, port=0)
    try:
        assert srv.health()["status"] == "ok"
        monkeypatch.setattr(backend, "_fallback_reason",
                            "plugin UNAVAILABLE (injected)")
        health = srv.health()
        assert health["status"] == "degraded"
        assert any("cpu_fallback" in r for r in health["reasons"])
    finally:
        srv._httpd.server_close()
