"""Model-zoo tests (serve/zoo.py): batched cross-model dispatch +
bounded admission/eviction.

* stacked-vs-solo BITWISE parity: a tenant served through the fused
  cross-model stack returns exactly the bytes its solo predictor would,
  across bucket boundaries, with quantized leaves, and alongside
  walk-path tenants (which never stack but still serve correctly);
* ONE fused launch per (stack, bucket): serving M co-batched tenants
  adds exactly one compile key — and the stacked jaxpr is loop-free
  (no lax.scan / while over tenants), the tree-sharded stacked program
  carries exactly ONE psum (asserted via the analysis walker);
* apply_delta lane splice: an in-envelope delta extend splices ONLY
  that tenant's lane of the stacked tables — zero recompiles, zero new
  compile keys, co-tenant outputs bit-identical before and after;
* eviction under traffic: every in-flight request either completes
  with correct values or fails with a typed error — never a torn
  result — and capacity evictions are counted, never silent;
* churn regression (compile-cache leak fix): repeated load/evict keeps
  the process-wide dispatch mirror and the metric series set bounded.
"""

import os
import threading
import time

import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.analysis import ir
from lightgbm_tpu.models.dense_predict import (make_stacked_sharded_predict,
                                               stack_dense_arrays,
                                               stacked_predict_raw)
from lightgbm_tpu.publish.delta import DeltaJournal
from lightgbm_tpu.resilience.admission import (DeadlineExceeded,
                                               QueueFullError, ServerClosed)
from lightgbm_tpu.serve.batcher import TenantQueueFull
from lightgbm_tpu.serve.predictor import compile_key_count
from lightgbm_tpu.serve.registry import ModelRegistry
from lightgbm_tpu.serve.zoo import ModelZoo
from lightgbm_tpu.telemetry.metrics import default_registry

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}

# row counts straddling the 8/64 bucket boundaries of the serve ladder
BOUNDARY_NS = (1, 7, 8, 9, 63, 64, 65)


def _train_variant(binary_data, seed, rounds=5, **extra):
    """One tenant's model: same features, per-seed label noise, so the
    ensembles differ but the lowered table shapes (and therefore the
    zoo's stack signature) coincide."""
    X, y = binary_data
    yv = np.asarray(y, np.float64)
    if seed:
        rng = np.random.RandomState(seed)
        yv = np.where(rng.rand(len(yv)) < 0.08, 1.0 - yv, yv)
    p = {**SMALL, "objective": "binary", **extra}
    return lgb.train(p, lgb.Dataset(X, yv, params=p), rounds)


def _model_dir(tmp_path, binary_data, names, **extra):
    d = tmp_path / "models"
    d.mkdir(exist_ok=True)
    for i, name in enumerate(names):
        _train_variant(binary_data, seed=i, **extra).save_model(
            str(d / f"{name}.txt"))
    return str(d)


def _series_total() -> int:
    return sum(len(m.series()) for m in default_registry().collect())


# ---------------------------------------------------------------------------
# stacked-vs-solo bitwise parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kwargs", [
    {},
    {"leaf_bits": 8},
], ids=["dense", "quantized-leaf8"])
def test_stacked_parity_bitwise(tmp_path, binary_data, kwargs):
    """A tenant's answer through the fused cross-model launch is BITWISE
    the answer its solo predictor gives, across bucket boundaries and
    for raw and transformed scores."""
    X, _ = binary_data
    names = ["m0", "m1", "m2"]
    d = _model_dir(tmp_path, binary_data, names)
    zoo = ModelZoo(stacking=True, batching=True, max_wait_ms=1.0)
    try:
        for n in names:
            zoo.load(n, os.path.join(d, f"{n}.txt"), **kwargs)
        groups = zoo.stack_membership()
        assert groups and sorted(sum(groups.values(), [])) == names, \
            f"same-shape tenants must co-stack, got {groups}"
        solo = ModelRegistry()
        rng = np.random.RandomState(0)
        for n in names:
            solo.load(f"solo-{n}", os.path.join(d, f"{n}.txt"),
                      warmup=False, **kwargs)
        for rows in BOUNDARY_NS:
            Xq = rng.randn(rows, X.shape[1]).astype(np.float32)
            for n in names:
                ref = np.asarray(solo.get(f"solo-{n}").predict(Xq))
                got = np.asarray(zoo.predict(n, Xq))
                assert np.array_equal(got, ref), \
                    f"{n} rows={rows}: stacked != solo (probabilities)"
                ref_r = np.asarray(
                    solo.get(f"solo-{n}").predict(Xq, raw_score=True))
                got_r = np.asarray(zoo.predict(n, Xq, raw_score=True))
                assert np.array_equal(got_r, ref_r), \
                    f"{n} rows={rows}: stacked != solo (raw)"
    finally:
        zoo.close()


def test_stacked_parity_concurrent_super_batch(tmp_path, binary_data):
    """Concurrent submits from every tenant land in ONE coalescing
    window (a genuine multi-lane super-batch) and each still gets its
    solo-identical slice back."""
    X, _ = binary_data
    names = ["m0", "m1", "m2", "m3"]
    d = _model_dir(tmp_path, binary_data, names)
    zoo = ModelZoo(stacking=True, batching=True, max_wait_ms=25.0)
    solo = ModelRegistry()
    try:
        for n in names:
            zoo.load(n, os.path.join(d, f"{n}.txt"))
            solo.load(n, os.path.join(d, f"{n}.txt"), warmup=False)
        rng = np.random.RandomState(1)
        queries = {n: rng.randn(5 + i, X.shape[1]).astype(np.float32)
                   for i, n in enumerate(names)}
        # warm the (stack, bucket) program so the timed window is tight
        for n in names:
            zoo.predict(n, queries[n])
        results, errs = {}, []

        def hit(n):
            try:
                results[n] = np.asarray(zoo.predict(n, queries[n]))
            except Exception as exc:  # noqa: BLE001 - recorded for assert
                errs.append((n, exc))
        threads = [threading.Thread(target=hit, args=(n,)) for n in names]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, f"concurrent stacked predicts failed: {errs}"
        for n in names:
            ref = np.asarray(solo.get(n).predict(queries[n]))
            assert np.array_equal(results[n], ref), \
                f"{n}: super-batched slice != solo"
    finally:
        zoo.close()


def test_walk_tenant_serves_but_never_stacks(tmp_path, binary_data):
    """A walk-path tenant (no dense tables) rides its own solo batcher:
    correct answers, no stack membership — and it does not poison the
    dense tenants' stack."""
    X, _ = binary_data
    d = _model_dir(tmp_path, binary_data, ["m0", "m1"])
    zoo = ModelZoo(stacking=True, batching=True, max_wait_ms=1.0)
    try:
        zoo.load("m0", os.path.join(d, "m0.txt"))
        zoo.load("m1", os.path.join(d, "m1.txt"))
        zoo.load("w0", os.path.join(d, "m0.txt"), compiler="walk")
        info = zoo.info()
        assert not info["w0"]["stackable"] and info["w0"]["stack"] is None
        members = sum(zoo.stack_membership().values(), [])
        assert "w0" not in members
        assert sorted(members) == ["m0", "m1"]
        solo = ModelRegistry()
        solo.load("ref", os.path.join(d, "m0.txt"), warmup=False,
                  compiler="walk")
        Xq = X[:9].astype(np.float32)
        assert np.array_equal(np.asarray(zoo.predict("w0", Xq)),
                              np.asarray(solo.get("ref").predict(Xq)))
    finally:
        zoo.close()


# ---------------------------------------------------------------------------
# one fused launch per (stack, bucket)
# ---------------------------------------------------------------------------

def test_one_compile_key_per_stack_bucket(tmp_path, binary_data):
    """Serving M tenants of one stack at one bucket adds exactly ONE
    entry to the process-wide dispatch mirror — one fused program, not
    one per tenant — and a second bucket adds exactly one more."""
    X, _ = binary_data
    names = ["m0", "m1", "m2"]
    d = _model_dir(tmp_path, binary_data, names)
    zoo = ModelZoo(stacking=True, batching=True, max_wait_ms=1.0)
    try:
        for n in names:
            zoo.load(n, os.path.join(d, f"{n}.txt"))
        rng = np.random.RandomState(2)
        before = compile_key_count()
        for n in names:  # all pad to the 8-row bucket
            zoo.predict(n, rng.randn(5, X.shape[1]))
        assert compile_key_count() == before + 1, \
            "M tenants at one bucket must share ONE fused program"
        for n in names:  # all pad to the 64-row bucket
            zoo.predict(n, rng.randn(33, X.shape[1]))
        assert compile_key_count() == before + 2
        snap = default_registry().get("zoo_stack_batches_total").series()
        assert sum(v for _lbl, v in snap) >= 6
    finally:
        zoo.close()


def test_stacked_jaxpr_loop_free_one_launch(binary_data):
    """The analysis walker on the stacked program: no per-tenant loop
    primitive survives tracing (the model axis is a vmapped batch dim of
    ONE fused launch, not an unrolled or scanned dispatch)."""
    X, _ = binary_data
    bst = _train_variant(binary_data, seed=0)
    reg = ModelRegistry()
    reg.load("m", bst.model_to_string(), warmup=False)
    exe = reg.get("m")._dense
    assert exe is not None and not exe.shard
    host = jax.device_get(exe.arrays)
    stacked = stack_dense_arrays([host] * 3)
    Xs = np.zeros((3, 64, X.shape[1]), np.float32)
    jx = ir.trace(lambda Xa, S: stacked_predict_raw(Xa, S, exe.meta),
                  Xs, stacked)
    for loop_prim in ("while", "scan", "fori_loop"):
        assert ir.count_primitive(jx, loop_prim) == 0, \
            f"stacked dispatch must be loop-free, found {loop_prim}"


def test_sharded_stack_exactly_one_psum(binary_data):
    """Tree-sharded stacked program: ONE psum of the (M, bucket, class)
    partials per launch — one collective per STACK, never per tenant
    (the serve/zoo_stack/score_psum contract, asserted directly)."""
    X, _ = binary_data
    bst = _train_variant(binary_data, seed=0)
    reg = ModelRegistry()
    reg.load("s", bst.model_to_string(), warmup=False, shard=4)
    exe = reg.get("s")._dense
    assert exe is not None and exe.shard == 4
    host = jax.device_get(exe.arrays)
    stacked = stack_dense_arrays([host] * 3)
    fn = make_stacked_sharded_predict(stacked, exe.meta, exe._mesh)
    Xs = np.zeros((3, 64, X.shape[1]), np.float32)
    colls = ir.collect_collectives(lambda Xa, S: fn(Xa, S), Xs, stacked)
    assert sum(len(v) for k, v in colls.items() if "psum" in k) == 1, \
        f"sharded stack must carry exactly one psum, got {colls}"


# ---------------------------------------------------------------------------
# apply_delta lane splice
# ---------------------------------------------------------------------------

def test_apply_delta_splices_one_lane_zero_recompiles(tmp_path,
                                                      binary_data,
                                                      monkeypatch):
    """An in-envelope delta extend splices ONLY that tenant's stack
    lane: same signature, zero recompiles, zero new compile keys, and
    the co-tenant's bytes are untouched.

    shard=4 pads the 1-tree base to capacity 4; the executable must
    stay UNSHARDED to be stackable, so the loads see a 1-device world
    (on one device the shard request degrades to pure tree-axis
    padding — exactly the envelope-without-mesh configuration a small
    zoo host runs)."""
    X, y = binary_data
    jdir = tmp_path / "journal"
    p = {**SMALL, "objective": "binary", "publish_dir": str(jdir),
         "publish_every": 1}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 2)
    mfile = str(tmp_path / "model.txt")
    bst.save_model(mfile)
    j = DeltaJournal(str(jdir))
    base_path, base_round = j.base_entry()

    zoo = ModelZoo(stacking=True, batching=True, max_wait_ms=1.0)
    try:
        real_devices = jax.devices
        with monkeypatch.context() as m:
            m.setattr(jax, "devices",
                      lambda *a, **kw: real_devices(*a, **kw)[:1])
            zoo.load("a", base_path, shard=4)
            zoo.load("b", base_path, shard=4)
        pa, pb = zoo.peek("a"), zoo.peek("b")
        assert pa.stackable and pb.stackable
        assert pa.info()["dense"]["capacity"] == 4
        sig = pa.signature
        stack_before = zoo.current_stack(sig)
        assert stack_before is not None and stack_before.width == 2

        rng = np.random.RandomState(3)
        queries = [rng.randn(n, X.shape[1]).astype(np.float32)
                   for n in (1, 7, 9)]
        b_before = [np.asarray(zoo.predict("b", Xq)) for Xq in queries]
        for Xq in queries:
            zoo.predict("a", Xq)
        (rec,) = j.records_after(base_round)
        # cold-load reference at the delta round, predicted OUTSIDE the
        # measured window (its solo dispatches own compile keys too)
        cold = ModelRegistry()
        cold.load("cold", mfile, warmup=False, num_iteration=rec.round)
        a_refs = [np.asarray(cold.get("cold").predict(Xq))
                  for Xq in queries]
        keys_before = compile_key_count()
        recompiles_before = pb.stats.snapshot()["recompiles"]

        out = zoo.apply_delta("a", rec)
        assert out["mode"] == "extend"
        # the splice replaced the stack object but kept its signature
        # (and therefore the fused program's jit-cache entry)
        stack_after = zoo.current_stack(sig)
        assert stack_after is not stack_before
        assert stack_after.names == stack_before.names
        assert stack_after.signature == stack_before.signature

        # grown tenant now answers like a cold load at the new round...
        for Xq, ref in zip(queries, a_refs):
            got = np.asarray(zoo.predict("a", Xq))
            assert np.array_equal(got, ref), \
                "spliced lane != cold load at the delta round"
        # ...the co-tenant's lane is bit-for-bit untouched...
        for Xq, ref in zip(queries, b_before):
            assert np.array_equal(np.asarray(zoo.predict("b", Xq)), ref), \
                "co-tenant bytes changed across a neighbour's splice"
        # ...and nothing recompiled anywhere
        assert compile_key_count() == keys_before, \
            "in-envelope splice must not mint new compile keys"
        assert zoo.peek("b").stats.snapshot()["recompiles"] == \
            recompiles_before
    finally:
        zoo.close()


# ---------------------------------------------------------------------------
# admission / eviction
# ---------------------------------------------------------------------------

def test_cold_load_on_miss_and_capacity_eviction(tmp_path, binary_data):
    """A request for a non-resident model cold-loads it through the
    resolver; over budget the coldest tenant is evicted (counted, never
    silent); an unknown name stays a typed KeyError."""
    X, _ = binary_data
    names = ["m0", "m1", "m2"]
    d = _model_dir(tmp_path, binary_data, names)
    zoo = ModelZoo(max_resident=2, source_resolver=d,
                   stacking=True, batching=False)
    try:
        evi = default_registry().get("zoo_evictions_total")
        cold = default_registry().get("zoo_cold_loads_total")
        evi_0 = sum(v for lbl, v in evi.series()
                    if lbl.get("reason") == "capacity")
        cold_0 = sum(v for _lbl, v in cold.series())
        Xq = X[:4].astype(np.float32)
        out = zoo.predict("m0", Xq)          # miss -> cold load
        assert out.shape == (4,)
        zoo.predict("m1", Xq)                # miss -> cold load
        zoo.predict("m0", Xq)                # m0 hotter than m1
        zoo.predict("m2", Xq)                # miss -> evicts coldest (m1)
        assert sorted(zoo.registry.names()) == ["m0", "m2"]
        assert sum(v for _lbl, v in cold.series()) == cold_0 + 3
        assert sum(v for lbl, v in evi.series()
                   if lbl.get("reason") == "capacity") == evi_0 + 1
        with pytest.raises(KeyError, match="nope"):
            zoo.predict("nope", Xq)
        # a re-request of the victim cold-loads it right back
        assert zoo.predict("m1", Xq).shape == (4,)
    finally:
        zoo.close()


@pytest.mark.slow
def test_eviction_under_traffic_never_torn(tmp_path, binary_data):
    """Hammer a 6-tenant zipfish workload through a 3-resident zoo while
    eviction churn runs: every request either completes with CORRECT
    bytes or raises a typed shed/evict error — never a torn or
    wrong-tenant result."""
    X, _ = binary_data
    names = [f"m{i}" for i in range(6)]
    d = _model_dir(tmp_path, binary_data, names, rounds=3)
    zoo = ModelZoo(max_resident=3, source_resolver=d,
                   stacking=True, batching=True, max_wait_ms=1.0)
    solo = ModelRegistry()
    rng = np.random.RandomState(4)
    Xq = rng.randn(5, X.shape[1]).astype(np.float32)
    refs = {}
    for n in names:
        solo.load(n, os.path.join(d, f"{n}.txt"), warmup=False)
        refs[n] = np.asarray(solo.get(n).predict(Xq))
    torn, typed, ok = [], [], [0]
    stop = time.monotonic() + 2.0
    lock = threading.Lock()

    def worker(wid):
        r = np.random.RandomState(wid)
        while time.monotonic() < stop:
            n = names[min(int(r.zipf(1.5)) - 1, 5)]
            try:
                got = np.asarray(zoo.predict(n, Xq, timeout_s=10.0))
            except (ServerClosed, DeadlineExceeded, QueueFullError,
                    KeyError):
                with lock:
                    typed.append(n)
                continue
            except Exception as exc:  # noqa: BLE001 - recorded for assert
                with lock:
                    torn.append((n, repr(exc)))
                continue
            if np.array_equal(got, refs[n]):
                with lock:
                    ok[0] += 1
            else:
                with lock:
                    torn.append((n, "wrong bytes"))
    threads = [threading.Thread(target=worker, args=(w,)) for w in range(6)]
    try:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not torn, f"torn/untyped results under eviction: {torn[:5]}"
        assert ok[0] > 50, "churn run served too little to prove anything"
        evi = default_registry().get("zoo_evictions_total")
        assert sum(v for lbl, v in evi.series()
                   if lbl.get("reason") == "capacity") > 0, \
            "test never actually evicted under traffic"
    finally:
        zoo.close()


def test_tenant_quota_sheds_before_shared_queue(tmp_path, binary_data):
    """One tenant's oversized burst is refused by ITS quota (typed 429
    + zoo_tenant_shed_total{model=...}) while the shared queue still
    has room — and the co-tenant keeps serving."""
    X, _ = binary_data
    d = _model_dir(tmp_path, binary_data, ["m0", "m1"])
    zoo = ModelZoo(stacking=True, batching=True, max_wait_ms=1.0,
                   tenant_queue_rows=4, max_queue_rows=1024)
    try:
        zoo.load("m0", os.path.join(d, "m0.txt"))
        zoo.load("m1", os.path.join(d, "m1.txt"))
        shed = default_registry().get("zoo_tenant_shed_total")
        shed_0 = sum(v for lbl, v in shed.series()
                     if lbl.get("model") == "m0")
        with pytest.raises(TenantQueueFull):
            zoo.predict("m0", np.zeros((8, X.shape[1]), np.float32))
        assert sum(v for lbl, v in shed.series()
                   if lbl.get("model") == "m0") == shed_0 + 1
        out = zoo.predict("m1", np.zeros((3, X.shape[1]), np.float32))
        assert out.shape == (3,)
    finally:
        zoo.close()


# ---------------------------------------------------------------------------
# churn regression: the compile-cache mirror and metric series stay bounded
# ---------------------------------------------------------------------------

def test_churn_keeps_compile_keys_and_series_bounded(tmp_path,
                                                     binary_data):
    """Load/serve/evict the same shapes repeatedly: after the first lap
    warms the caches, later laps add NO compile keys and NO metric
    series — the leak this PR's release path exists to prevent."""
    X, _ = binary_data
    d = _model_dir(tmp_path, binary_data, ["m0", "m1"])
    Xq = np.zeros((3, X.shape[1]), np.float32)

    def one_lap():
        zoo = ModelZoo(stacking=True, batching=True, max_wait_ms=1.0)
        try:
            zoo.load("churn-a", os.path.join(d, "m0.txt"))
            zoo.load("churn-b", os.path.join(d, "m1.txt"))
            zoo.predict("churn-a", Xq)
            zoo.predict("churn-b", Xq)
            assert zoo.evict("churn-a") and zoo.evict("churn-b")
        finally:
            zoo.close()

    one_lap()
    keys_1, series_1 = compile_key_count(), _series_total()
    for _ in range(4):
        one_lap()
    assert compile_key_count() == keys_1, \
        "zoo churn ratcheted the compile-key mirror"
    assert _series_total() == series_1, \
        "zoo churn ratcheted the metric series set"


def test_evict_releases_stack_and_member_keys(tmp_path, binary_data):
    """Evicting down to one tenant dissolves the stack and releases the
    fused program's dispatch-mirror entries; evicting the last model of
    the shape releases the member entries too."""
    X, _ = binary_data
    d = _model_dir(tmp_path, binary_data, ["m0", "m1"])
    before = compile_key_count()
    zoo = ModelZoo(stacking=True, batching=True, max_wait_ms=1.0)
    try:
        zoo.load("m0", os.path.join(d, "m0.txt"))
        zoo.load("m1", os.path.join(d, "m1.txt"))
        zoo.predict("m0", np.zeros((3, X.shape[1]), np.float32))
        assert compile_key_count() > before
        assert zoo.evict("m0")
        assert zoo.stack_membership() == {}
        assert zoo.evict("m1")
        assert compile_key_count() == before, \
            "last-of-shape eviction left compile keys behind"
    finally:
        zoo.close()


# ---------------------------------------------------------------------------
# introspection
# ---------------------------------------------------------------------------

def test_info_reports_group_and_stack_membership(tmp_path, binary_data):
    X, _ = binary_data
    d = _model_dir(tmp_path, binary_data, ["m0", "m1"])
    zoo = ModelZoo(stacking=True, batching=False, max_resident=8)
    try:
        zoo.load("m0", os.path.join(d, "m0.txt"))
        zoo.load("m1", os.path.join(d, "m1.txt"))
        info = zoo.info()
        for n in ("m0", "m1"):
            ent = info[n]
            assert ent["stackable"] is True
            assert ent["group_key"] == zoo.peek(n).group_key
            assert ent["stack"]["members"] == ["m0", "m1"]
            assert ent["stack"]["width"] == 2
            assert ent["stack"]["lane"] == ("m0", "m1").index(n)
            assert ent["stack"]["group"] in zoo.stack_membership()
        zs = zoo.zoo_stats()
        assert zs["resident"] == 2 and zs["max_resident"] == 8
        assert zs["stacking"] is True
        assert sorted(sum(zs["groups"].values(), [])) == ["m0", "m1"]
        assert set(zs["traffic_weight"]) == {"m0", "m1"}
    finally:
        zoo.close()
