"""SLO engine + coverage lint + serving endpoints (fleet observability).

Covers: declarative SLO registration and the burn-rate math (ratio and
latency kinds, multi-window breach semantics, sustained-fast-burn
degradation), the slowest-request exemplar ring, the analysis/
SLO-coverage check (clean at head; planted dangling-metric and
bad-selector SLOs fail with site-named diagnostics, the
note_collective-contract coverage pattern), and the HTTP surface:
``GET /slo``, SLO-aware ``/healthz``, ``X-Request-Id`` propagation and
the batcher saturation gauges on ``/stats``.
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry.metrics import MetricsRegistry
from lightgbm_tpu.telemetry.slo import (ExemplarRing, SloEngine, all_slos,
                                        remove_slo, slo)


# ---------------------------------------------------------------------------
# engine math
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def test_ratio_slo_burn_math():
    reg = MetricsRegistry()
    bad = reg.counter("t_bad_total", labels=())
    total = reg.counter("t_total", labels=())
    slo("test/ratio", metric="t_bad_total", total_metric="t_total",
        kind="ratio", target=0.99, window_fast_s=60, window_slow_s=600,
        burn_fast=10.0, burn_slow=5.0)
    try:
        clk = _Clock()
        eng = SloEngine(registry=reg, sustain=2, clock=clk)
        total.inc(100)
        r = eng.evaluate()
        v = next(s for s in r["slos"] if s["name"] == "test/ratio")
        assert v["ok"] and v["burn"]["fast"] == 0.0

        # burn: 20% errors against a 1% budget = 20x in both windows
        clk.t = 10.0
        bad.inc(25)
        total.inc(125)
        r = eng.evaluate()
        v = next(s for s in r["slos"] if s["name"] == "test/ratio")
        assert v["error_ratio"]["fast"] == pytest.approx(0.2)
        assert v["burn"]["fast"] == pytest.approx(20.0)
        assert v["breached"] and not v["ok"]
        assert "test/ratio" in r["breached"]

        # sustained fast burn flips the engine's degraded list
        clk.t = 20.0
        bad.inc(25)
        total.inc(125)
        r = eng.evaluate()
        assert "test/ratio" in r["degraded"]
        assert "test/ratio" in eng.degraded()

        # recovery: clean traffic dilutes the fast window below threshold
        clk.t = 90.0               # the hot samples age out of fast (60s)
        total.inc(10000)
        r = eng.evaluate()
        v = next(s for s in r["slos"] if s["name"] == "test/ratio")
        assert v["burn"]["fast"] < 10.0
        assert not v["fast_burning"]
        assert eng.degraded() == []
    finally:
        remove_slo("test/ratio")


def test_ratio_slo_idle_service_does_not_burn():
    reg = MetricsRegistry()
    reg.counter("t2_bad_total")
    reg.counter("t2_total")
    slo("test/idle", metric="t2_bad_total", total_metric="t2_total",
        kind="ratio", target=0.999)
    try:
        eng = SloEngine(registry=reg, clock=_Clock())
        for _ in range(3):
            r = eng.evaluate()
        v = next(s for s in r["slos"] if s["name"] == "test/idle")
        assert v["ok"] and v["burn"]["fast"] == 0.0
    finally:
        remove_slo("test/idle")


def test_latency_slo_per_bucket_series():
    reg = MetricsRegistry()
    h = reg.histogram("t_lat_ms", labels=("model", "bucket"))
    for _ in range(50):
        h.observe(5.0, model="m", bucket="64")     # fast bucket
        h.observe(80.0, model="m", bucket="4096")  # slow bucket
    slo("test/lat", metric="t_lat_ms", kind="latency", target=0.9,
        threshold_ms=50.0, burn_fast=5.0, burn_slow=3.0)
    try:
        clk = _Clock()
        eng = SloEngine(registry=reg, clock=clk)
        r = eng.evaluate()
        v = next(s for s in r["slos"] if s["name"] == "test/lat")
        series = {tuple(sorted(s["labels"].items())): s
                  for s in v["detail"]["series"]}
        fast = series[(("bucket", "64"), ("model", "m"))]
        slow = series[(("bucket", "4096"), ("model", "m"))]
        assert fast["frac_over"] == 0.0 and slow["frac_over"] == 1.0
        assert slow["p99_ms"] == pytest.approx(80.0)
        # worst series drives the burn: 100% over vs 10% budget = 10x
        assert v["burn"]["fast"] == pytest.approx(10.0)
        assert v["breached"]
        # burn-rate gauges landed back in the registry (Prometheus path)
        g = reg.get("slo_burn_rate")
        assert g is not None and any(
            lbl == {"slo": "test/lat", "window": "fast"} and val > 0
            for lbl, val in g.series())

        # recovery without traffic: the count-bounded histogram window
        # stays hot forever, but idle evaluations must contribute zero
        # burn (stale window != live burst) so the breach clears once
        # the burst ages out of the fast window
        for clk.t in (60.0, 120.0, 180.0, 240.0, 330.0, 400.0):
            r = eng.evaluate()
        v = next(s for s in r["slos"] if s["name"] == "test/lat")
        assert v["burn"]["fast"] < 5.0 and not v["breached"], v
    finally:
        remove_slo("test/lat")


def test_gauge_floor_slo_burns_while_below_floor():
    """The fleet supervision kind: a gauge under its floor spends
    budget per scrape; recovery + no-data scrapes decay the burn."""
    reg = MetricsRegistry()
    g = reg.gauge("t_workers_alive")
    slo("test/floor", metric="t_workers_alive", kind="gauge_floor",
        floor=1.0, target=0.5, window_fast_s=60, window_slow_s=600,
        burn_fast=1.9, burn_slow=1.5)
    try:
        clk = _Clock()
        eng = SloEngine(registry=reg, sustain=2, clock=clk)
        # no series yet: a booting fleet must not page
        r = eng.evaluate()
        v = next(s for s in r["slos"] if s["name"] == "test/floor")
        assert v["ok"] and v["burn"]["fast"] == 0.0
        assert v["detail"]["value"] is None

        g.set(2.0)
        clk.t = 10.0
        r = eng.evaluate()
        v = next(s for s in r["slos"] if s["name"] == "test/floor")
        assert v["ok"] and v["burn"]["fast"] == 0.0

        # the whole fleet down: every scrape errors -> burn 2.0 over
        # the 0.5 budget, breaching both windows once the down scrapes
        # fill the fast window
        g.set(0.0)
        for clk.t in (20.0, 30.0, 40.0, 50.0, 60.0, 70.0, 80.0, 90.0):
            r = eng.evaluate()
        v = next(s for s in r["slos"] if s["name"] == "test/floor")
        assert v["burn"]["fast"] > 1.9 and v["breached"], v
        assert v["detail"]["floor"] == 1.0 and v["detail"]["value"] == 0

        # recovery: alive again, the hot samples age out of fast
        g.set(2.0)
        for clk.t in (100.0, 110.0, 160.0, 230.0):
            r = eng.evaluate()
        v = next(s for s in r["slos"] if s["name"] == "test/floor")
        assert not v["breached"] and v["burn"]["fast"] < 1.9, v
    finally:
        remove_slo("test/floor")


def test_exemplar_ring_keeps_worst_n():
    ring = ExemplarRing(capacity=4)
    for i in range(100):
        ring.offer(float(i), {"request_id": f"r{i}"})
    snap = ring.snapshot()
    assert [e["score"] for e in snap] == [99.0, 98.0, 97.0, 96.0]
    assert snap[0]["request_id"] == "r99"
    assert len(ring) == 4


# ---------------------------------------------------------------------------
# coverage lint (analysis/slo_cover.py)
# ---------------------------------------------------------------------------

def test_slo_coverage_clean_at_head():
    from lightgbm_tpu.analysis.slo_cover import check_slo_coverage
    assert check_slo_coverage() == []
    # the shipped objectives are all declared
    names = set(all_slos())
    assert {"serve/latency_p99", "serve/availability", "serve/shed_rate",
            "serve/compiler_fallback_rate", "fleet/workers_alive",
            "fleet/retry_rate", "serve/explain_latency_p99"} <= names


def test_explain_slo_covered_and_planted_violation_fails():
    """The /explain lane's latency objective keys to a registered
    WindowedHistogram (slo_cover validates it at head), and a planted
    broken twin — the same threshold pointed at the lane's COUNTER —
    fails coverage: the lint genuinely checks the explain series."""
    from lightgbm_tpu.analysis.slo_cover import check_slo_coverage
    rep = all_slos()["serve/explain_latency_p99"]
    assert rep.metric == "serve_explain_latency_ms"
    assert rep.kind == "latency" and rep.threshold_ms > 0
    slo("test/explain_latency_on_counter",
        metric="serve_explain_requests_total", kind="latency",
        target=0.99, threshold_ms=2000.0)
    try:
        vs = check_slo_coverage()
        assert any(v.site == "test/explain_latency_on_counter"
                   for v in vs)
        assert not any(v.site == "serve/explain_latency_p99" for v in vs)
    finally:
        remove_slo("test/explain_latency_on_counter")
    assert check_slo_coverage() == []


def test_planted_dangling_metric_fails_coverage():
    from lightgbm_tpu.analysis.slo_cover import check_slo_coverage
    slo("test/dangling", metric="no_such_series_total",
        total_metric="serve_requests_total", kind="ratio", target=0.99)
    try:
        vs = check_slo_coverage()
        assert any(v.site == "test/dangling" and
                   "no_such_series_total" in v.message for v in vs)
    finally:
        remove_slo("test/dangling")
    assert check_slo_coverage() == []


def test_planted_bad_selector_and_kind_fail_coverage():
    from lightgbm_tpu.analysis.slo_cover import check_slo_coverage
    # selector on a label the series never carries
    slo("test/bad_label", metric="serve_http_responses_total",
        total_metric="serve_http_responses_total", kind="ratio",
        target=0.999, bad_labels={"status_klasse": "5*"})
    # latency SLO pointed at a counter
    slo("test/bad_kind", metric="serve_requests_total", kind="latency",
        target=0.99, threshold_ms=10.0)
    try:
        sites = {v.site for v in check_slo_coverage()}
        assert {"test/bad_label", "test/bad_kind"} <= sites
    finally:
        remove_slo("test/bad_label")
        remove_slo("test/bad_kind")


def test_planted_gauge_floor_violations_fail_coverage():
    from lightgbm_tpu.analysis.slo_cover import check_slo_coverage
    # gauge_floor pointed at a counter
    slo("test/floor_on_counter", metric="serve_requests_total",
        kind="gauge_floor", floor=1.0, target=0.5)
    # gauge_floor with no floor declared
    slo("test/floor_zero", metric="fleet_workers_alive",
        kind="gauge_floor", target=0.5)
    try:
        vs = check_slo_coverage()
        by_site = {v.site: v.message for v in vs}
        assert "needs a gauge" in by_site["test/floor_on_counter"]
        assert "floor > 0" in by_site["test/floor_zero"]
    finally:
        remove_slo("test/floor_on_counter")
        remove_slo("test/floor_zero")
    assert check_slo_coverage() == []


def test_lint_trace_report_carries_slo_section():
    from lightgbm_tpu.analysis.slo_cover import slo_coverage_report
    rep = slo_coverage_report()
    assert rep["ok"] and "serve/latency_p99" in rep["slos"]
    assert rep["slos"]["serve/latency_p99"]["metric"] == \
        "serve_request_latency_ms"


# ---------------------------------------------------------------------------
# HTTP surface e2e
# ---------------------------------------------------------------------------

def _mk_server(tmp_path, **kw):
    from lightgbm_tpu.serve.registry import ModelRegistry
    from lightgbm_tpu.serve.server import PredictionServer
    rng = np.random.RandomState(0)
    X = rng.randn(400, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 5)
    mf = os.path.join(str(tmp_path), "m.txt")
    bst.save_model(mf)
    reg = ModelRegistry()
    reg.load("m", mf, warmup=False)
    return PredictionServer(reg, port=0, **kw).start(), X


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.status, json.loads(r.read().decode())


def test_server_slo_endpoint_and_request_id(tmp_path):
    srv, X = _mk_server(tmp_path)
    try:
        body = json.dumps({"rows": X[:3].tolist()}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/predict", data=body,
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "e2e-42"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            assert r.headers.get("X-Request-Id") == "e2e-42"
            out = json.loads(r.read().decode())
        assert out["request_id"] == "e2e-42"
        # a request without the header gets a server-assigned id
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/predict", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req2, timeout=30) as r:
            out2 = json.loads(r.read().decode())
        assert out2["request_id"].startswith("srv-")

        code, rep = _get(srv.port, "/slo")
        assert code == 200 and rep["schema"] == "slo-report-v1"
        names = {s["name"] for s in rep["slos"]}
        assert "serve/latency_p99" in names and \
            "serve/availability" in names

        code, health = _get(srv.port, "/healthz")
        assert code == 200 and health["status"] in ("ok", "degraded")

        # a request naming the model explicitly must share the nameless
        # requests' batcher (one saturation entry, no "default" alias
        # clobbering the gauges)
        req3 = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/predict",
            data=json.dumps({"rows": X[:2].tolist(),
                             "model": "m"}).encode(),
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req3, timeout=30).read()

        code, stats = _get(srv.port, "/stats")
        assert code == 200
        assert list(stats) == ["m"]
        assert "saturation" in stats["m"]
        assert stats["m"]["saturation"]["inflight_requests"] == 0
        # the per-request timing split made it to /stats
        assert stats["m"]["request_latency_ms"]["window"] >= 2
        assert stats["m"]["queue_wait_ms"]["window"] >= 2
    finally:
        srv.shutdown()


def test_healthz_degrades_on_sustained_fast_burn(tmp_path):
    from lightgbm_tpu.telemetry.slo import SloEngine
    from lightgbm_tpu.serve.stats import request_exemplars
    # a private engine wired into the server, with a planted objective
    # reading the DEFAULT registry's request-latency series (the server
    # records into the default registry through ModelStats)
    slo("test/hot", metric="serve_request_latency_ms", kind="latency",
        target=0.99, threshold_ms=1e-6, burn_fast=1.0, burn_slow=1.0)
    try:
        eng = SloEngine(sustain=2)  # default registry
        # the ring keeps the process-wide slowest N: drop whatever
        # earlier tests parked there so this test's requests qualify
        request_exemplars().clear()
        srv, X = _mk_server(tmp_path, slo_engine=eng)
        try:
            body = json.dumps({"rows": X[:3].tolist()}).encode()
            for i in range(3):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}/predict", data=body,
                    headers={"Content-Type": "application/json",
                             "X-Request-Id": f"hot-{i}"})
                urllib.request.urlopen(req, timeout=30).read()
            # every request is over the absurd threshold -> sustained
            # fast burn after two evaluations
            _get(srv.port, "/slo")
            code, health = _get(srv.port, "/healthz")
            assert health["status"] == "degraded"
            assert any("slo_fast_burn: test/hot" in r
                       for r in health.get("reasons", []))
            # the /slo payload attaches the exemplar ring on a burn
            code, rep = _get(srv.port, "/slo")
            assert "exemplars" in rep and rep["exemplars"]
            ids = {e["request_id"] for e in rep["exemplars"]}
            assert any(i.startswith("hot-") for i in ids)
        finally:
            srv.shutdown()
    finally:
        remove_slo("test/hot")
    assert request_exemplars().snapshot() is not None


def test_fallback_batches_counter_measures_traffic():
    """The fallback SLO's numerator moves per SERVED BATCH, not per
    compile — a fallback-built predictor's traffic is what burns."""
    from lightgbm_tpu.serve.compiler import FALLBACK_BATCHES
    from lightgbm_tpu.telemetry.metrics import default_registry
    rng = np.random.RandomState(0)
    X = rng.randn(300, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 5)
    pred = bst.to_predictor(warmup=False, compiler="walk")
    c = default_registry().counter(FALLBACK_BATCHES,
                                   labels=("reason", "model"))
    before = c.value(reason="forced_walk", model="default")
    pred.predict(X[:3])
    pred.predict(X[:3])
    assert c.value(reason="forced_walk", model="default") == before + 2


def test_availability_counter_counts_5xx(tmp_path):
    from lightgbm_tpu.telemetry.metrics import default_registry
    srv, X = _mk_server(tmp_path)
    try:
        c = default_registry().get("serve_http_responses_total")
        before = c.value(code="404")
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/nope", timeout=30)
        except urllib.error.HTTPError as e:
            assert e.code == 404
        assert c.value(code="404") == before + 1
    finally:
        srv.shutdown()
