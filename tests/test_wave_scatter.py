"""Feature-sliced reduce-scatter histogram merging on the DP wave path
(ISSUE 5 tentpole; learner/wave.py use_scatter + WaveDPStrategy.
reduce_hist_scatter — the reference DP learner's ReduceScatter
refinement, data_parallel_tree_learner.cpp:155-173, amortized over the
wave's channels).

Contract under test:
  * bit-identity — with ``tpu_dp_hist_scatter=True`` the trained tree is
    IDENTICAL to the full-batch-psum DP path and to the serial grower
    (quantized path: bit-for-bit, integer channel sums reduce exactly;
    f32: prediction-tolerance, like the existing DP parity tests);
  * collective shape — the traced program contains exactly one
    ``reduce_scatter`` per histogram-merge site and ZERO full-histogram
    ``psum``s: every remaining psum operand is O(W*k) winner-exchange /
    leaf-totals sized;
  * fallback — categorical / forced-split configs with the flag ON fall
    back to the psum merge and still reproduce serial training;
  * telemetry — collectives_snapshot() shows the per-pass histogram
    bytes dropping by >= 4x at k=8.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

import lightgbm_tpu as lgb
from lightgbm_tpu.learner.wave import make_wave_grow_fn
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.parallel.data_parallel import (DataParallelTreeLearner,
                                                 WaveDPStrategy)
from lightgbm_tpu.parallel.mesh import get_mesh, shard_map_compat

F, B, LEAVES, WAVE = 6, 64, 13, 4


def _mk_data(seed=0):
    rng = np.random.RandomState(seed)
    n = 8 * 4096
    bins = rng.randint(0, B - 1, (F, n)).astype(np.uint8)
    logit = (bins[0].astype(np.float32) / B - 0.5) * 3 + \
        ((bins[1] > 40).astype(np.float32) - 0.5) * 2
    y = (logit + rng.randn(n) * 0.7 > 0).astype(np.float32)
    grad = (0.5 - y).astype(np.float32)
    hess = np.full(n, 0.25, np.float32)
    mask = np.ones(n, np.float32)
    return (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask))


def _mk_grow(strategy, quantized=True, spec=False):
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                     any_cat=False)
    return make_wave_grow_fn(
        num_leaves=LEAVES, num_features=F, max_bins=B, max_depth=0,
        split_params=sp, hist_impl="pallas", any_cat=False, interpret=True,
        jit=False, wave_size=WAVE, quantized=quantized, stochastic=False,
        spec_ramp=spec, spec_tol=0.02, strategy=strategy)


def _wrap_dp(grow, mesh, ax):
    return jax.jit(shard_map_compat(
        lambda X_T, g, h, m, nb, ic, hn, mono, cp, fm: grow(
            X_T, g, h, m, nb, ic, hn, mono, cp, (), fm),
        mesh=mesh,
        in_specs=(P(None, ax), P(ax), P(ax), P(ax), P(), P(), P(), P(),
                  P(), P()),
        out_specs=DataParallelTreeLearner._tree_specs(ax)))


def _meta_args():
    return (jnp.full((F,), B, jnp.int32), jnp.zeros((F,), bool),
            jnp.zeros((F,), bool), jnp.zeros((F,), jnp.int32),
            jnp.zeros((F,), jnp.float32), jnp.ones((F,), bool))


def _serial_call(grow, data):
    bins, grad, hess, mask = data
    nb, ic, hn, mono, cp, fm = _meta_args()
    return grow(bins, grad, hess, mask, nb, ic, hn, mono, cp, (), fm)


BITWISE = ("num_leaves", "split_feature", "threshold_bin", "nan_bin",
           "decision_type", "left_child", "right_child", "row_leaf")


def test_scatter_matches_allreduce_and_serial_bitwise():
    """Quantized DP wave: scatter == psum == serial, bit-for-bit (the
    endgame engages at 13 leaves / wave 4, so the slice-local bank and
    the per-commit winner exchange are exercised too)."""
    mesh = get_mesh(8)
    ax = mesh.axis_names[0]
    data = _mk_data()
    args = data + _meta_args()
    t_ser = _serial_call(_mk_grow(None), data)
    t_ar = _wrap_dp(_mk_grow(WaveDPStrategy(ax, nshards=8)),
                    mesh, ax)(*args)
    t_sc = _wrap_dp(_mk_grow(WaveDPStrategy(ax, nshards=8,
                                            hist_scatter=True)),
                    mesh, ax)(*args)
    for name in BITWISE + ("split_gain", "leaf_value", "leaf_count"):
        np.testing.assert_array_equal(
            np.asarray(getattr(t_sc, name)),
            np.asarray(getattr(t_ar, name)),
            err_msg=f"scatter != allreduce: {name}")
    for name in BITWISE:
        np.testing.assert_array_equal(
            np.asarray(getattr(t_sc, name)),
            np.asarray(getattr(t_ser, name)),
            err_msg=f"scatter != serial: {name}")
    np.testing.assert_allclose(np.asarray(t_sc.leaf_value),
                               np.asarray(t_ser.leaf_value),
                               rtol=0, atol=1e-6)
    assert int(t_sc.hist_passes) == int(t_ser.hist_passes)


def test_scatter_spec_ramp_rides_the_scatter():
    """Spec ramp + scatter: the provisional passes reduce-scatter their
    subsample batches and the committed tree still equals serial spec
    growth bit-for-bit on the quantized path."""
    mesh = get_mesh(8)
    ax = mesh.axis_names[0]
    data = _mk_data(seed=3)
    args = data + _meta_args()
    t_ser = _serial_call(_mk_grow(None, spec=True), data)
    t_sc = _wrap_dp(_mk_grow(WaveDPStrategy(ax, nshards=8,
                                            hist_scatter=True), spec=True),
                    mesh, ax)(*args)
    for name in BITWISE:
        np.testing.assert_array_equal(
            np.asarray(getattr(t_sc, name)),
            np.asarray(getattr(t_ser, name)), err_msg=name)
    assert int(t_sc.hist_passes) == int(t_ser.hist_passes)


# ---------------------------------------------------------------------------
# Traced-program shape: one reduce_scatter per merge site, zero
# full-histogram psums, O(W*k) winner exchange.  The jaxpr traversal is
# the shared analysis.ir walker (this file's local copy moved there).
# ---------------------------------------------------------------------------

from lightgbm_tpu.analysis.ir import collect_collectives as _collectives_of


def test_scatter_traced_collectives_shape():
    """Jaxpr-level assertion (test_specramp style): the scatter program
    holds exactly one reduce_scatter per histogram-merge site (root +
    wave body + endgame body = 3 for the non-spec config), NO psum as
    large as a histogram batch, and a winner exchange per scan site."""
    mesh = get_mesh(8)
    ax = mesh.axis_names[0]
    args = _mk_data() + _meta_args()
    g_sc = _wrap_dp(_mk_grow(WaveDPStrategy(ax, nshards=8,
                                            hist_scatter=True)), mesh, ax)
    g_ar = _wrap_dp(_mk_grow(WaveDPStrategy(ax, nshards=8)), mesh, ax)
    coll_sc = _collectives_of(lambda *a: g_sc(*a), *args)
    coll_ar = _collectives_of(lambda *a: g_ar(*a), *args)

    # reduce-scatter name differs across jax versions; find it
    rs_names = [k for k in coll_sc if "reduce_scatter" in k]
    assert rs_names, f"no reduce_scatter traced: {sorted(coll_sc)}"
    n_rs = sum(len(coll_sc[k]) for k in rs_names)
    # one per merge site: root pass, wave-body pass, endgame-bank pass
    assert n_rs == 3, (n_rs, coll_sc)
    assert not any("reduce_scatter" in k for k in coll_ar), coll_ar

    # the allreduce program psums full (c, F, B, 3) histogram batches;
    # the scatter program must have NO psum bigger than the O(W*k)
    # winner-exchange payload / leaf-totals vectors
    hist_batch = WAVE * F * B * 3
    big_ar = [s for s in coll_ar.get("psum", []) if s >= hist_batch]
    assert big_ar, "allreduce baseline lost its histogram psum?"
    exchange_cap = 16 * max(2 * WAVE, LEAVES)
    big_sc = [s for s in coll_sc.get("psum", []) if s > exchange_cap]
    assert not big_sc, f"full-histogram psum leaked into scatter: {big_sc}"
    # winner exchange present: one pmax+pmin pair per scan site (root,
    # wave-body children, endgame-commit children)
    assert len(coll_sc.get("pmax", [])) >= 3
    assert len(coll_sc.get("pmin", [])) >= 3
    assert all(s <= exchange_cap for s in coll_sc["pmax"])


def test_scatter_telemetry_byte_ratio():
    """collectives_snapshot(): >= 4x fewer histogram bytes per merge at
    k=8 (F=6 pads to 8 blocks of 1 -> a 6x residency drop)."""
    from lightgbm_tpu.telemetry import _config as tele_config
    from lightgbm_tpu.telemetry.train_record import (collectives_reset,
                                                     collectives_snapshot)
    if not tele_config.enabled():
        pytest.skip("telemetry disabled via LGBM_TPU_TELEMETRY=0")
    mesh = get_mesh(8)
    ax = mesh.axis_names[0]
    args = _mk_data() + _meta_args()
    collectives_reset()
    g_sc = _wrap_dp(_mk_grow(WaveDPStrategy(ax, nshards=8,
                                            hist_scatter=True)), mesh, ax)
    jax.make_jaxpr(lambda *a: g_sc(*a))(*args)  # trace -> tally
    snap_sc = collectives_snapshot()
    collectives_reset()
    g_ar = _wrap_dp(_mk_grow(WaveDPStrategy(ax, nshards=8)), mesh, ax)
    jax.make_jaxpr(lambda *a: g_ar(*a))(*args)
    snap_ar = collectives_snapshot()
    collectives_reset()

    sc = snap_sc["data_parallel/wave/hist_reduce_scatter"]
    ar = snap_ar["data_parallel/wave/hist_psum"]
    assert sc["count"] == ar["count"] == 3  # root + body + endgame
    per_pass_sc = sc["bytes"] / sc["count"]
    per_pass_ar = ar["bytes"] / ar["count"]
    assert per_pass_ar >= 4 * per_pass_sc, (per_pass_ar, per_pass_sc)
    # and the winner exchange was tallied
    assert "data_parallel/wave/winner_exchange" in snap_sc


# ---------------------------------------------------------------------------
# Public-API parity: the config flag, NaN/monotone on the scatter path,
# cats + forced splits falling back to the psum merge
# ---------------------------------------------------------------------------

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1,
         "tree_grow_mode": "wave"}


@pytest.mark.parametrize("extra", [
    {},
    {"monotone_constraints": [1, 0, 0, 0, 0, 0]},
])
def test_dp_scatter_flag_matches_serial_api(extra):
    """lgb.train with tree_learner=data: scatter on == scatter off ==
    serial at prediction tolerance, with NaNs in one column and an
    optional monotone constraint (both ride the sliced scan)."""
    rng = np.random.RandomState(11)
    n = 704
    X = rng.randn(n, 6)
    X[rng.rand(n) < 0.1, 3] = np.nan
    y = ((np.nan_to_num(X[:, 0]) + 0.5 * X[:, 1] -
          np.nan_to_num(X[:, 3]) * 0.3) > 0).astype(np.float64)
    p = {**SMALL, "objective": "binary", **extra}
    serial = lgb.train(p, lgb.Dataset(X, y), 4).predict(X)
    preds = {}
    for flag in (True, False):
        bst = lgb.train({**p, "tree_learner": "data",
                         "tpu_dp_hist_scatter": flag},
                        lgb.Dataset(X, y), 4)
        preds[flag] = bst.predict(X)
    np.testing.assert_allclose(preds[True], preds[False], atol=2e-6,
                               err_msg="scatter flag changed the model")
    np.testing.assert_allclose(preds[True], serial, atol=2e-5)


def test_dp_scatter_cat_and_forced_fall_back_to_psum():
    """Categorical shapes keep the full-batch psum under the flag (the
    static cat_idx subset search indexes full feature space) and still
    reproduce serial training; same for forced splits."""
    rng = np.random.RandomState(9)
    n = 640
    c = rng.randint(0, 8, n).astype(float)
    x1 = rng.randn(n)
    y = np.where(c % 2 == 0, 1.5, -1.5) + x1 * 0.3
    X = np.stack([c, x1], 1)
    p = {**SMALL, "objective": "regression", "cat_smooth": 1.0,
         "min_data_per_group": 1}
    preds = {}
    for tl in ("serial", "data"):
        bst = lgb.train({**p, "tree_learner": tl,
                         "tpu_dp_hist_scatter": True},
                        lgb.Dataset(X, y, categorical_feature=[0]), 4)
        preds[tl] = bst.predict(X)
    np.testing.assert_allclose(preds["data"], preds["serial"], atol=2e-5)

    import json
    import tempfile
    fs = {"feature": 0, "threshold": 0.0,
          "left": {"feature": 1, "threshold": 0.2}}
    with tempfile.NamedTemporaryFile("w", suffix=".json",
                                     delete=False) as fh:
        json.dump(fs, fh)
        path = fh.name
    X2 = rng.randn(n, 4)
    y2 = (X2[:, 0] + 0.3 * X2[:, 1] > 0).astype(np.float64)
    pf = {**SMALL, "objective": "binary", "forcedsplits_filename": path,
          "tpu_dp_hist_scatter": True}
    want = lgb.train(pf, lgb.Dataset(X2, y2), 3).predict(X2)
    got = lgb.train({**pf, "tree_learner": "data"},
                    lgb.Dataset(X2, y2), 3).predict(X2)
    np.testing.assert_allclose(got, want, atol=2e-5)
