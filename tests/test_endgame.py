"""Exact device-side endgame tests (learner/wave.py + learner/endgame.py).

Once the remaining leaf budget drops below 2*wave_size, the wave grower
precomputes the frontier candidates' smaller-child histograms in ONE
batched pass and commits the remaining splits in the TRUE sequential
best-first order on-device.  Therefore:
  (a) with wave_size=1 (already sequential), endgame on/off must agree
      bit-for-bit;
  (b) when the WHOLE tree fits in the endgame (num_leaves - 1 < 2W), the
      grown tree must be IDENTICAL to the wave_size=1 sequential tree —
      the selector reproduces the exact leaf-wise order;
  (c) the endgame must spend no more full-data histogram passes than the
      halving taper it replaces (hist_passes counter);
  (d) held-out quality must be at least taper-par.
Growers run the real Pallas kernels in interpret mode on CPU; the XLA
fallback path is cross-checked against the Pallas path.
"""

import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.learner.wave import make_wave_grow_fn
from lightgbm_tpu.ops.histogram_pallas import pad_rows
from lightgbm_tpu.ops.split import SplitParams

F, B = 6, 64


def _mk_data(n_raw=6000, seed=0):
    rng = np.random.RandomState(seed)
    n = pad_rows(n_raw)
    bins = rng.randint(0, B - 1, (F, n)).astype(np.uint8)
    logit = (bins[0].astype(np.float32) / B - 0.5) * 3 + \
        ((bins[1] > 40).astype(np.float32) - 0.5) * 2 + \
        (bins[2].astype(np.float32) / B) * (bins[3] > 20)
    y = (logit + rng.randn(n) * 0.7 > 0).astype(np.float32)
    grad = (0.5 - y).astype(np.float32)
    hess = np.full(n, 0.25, np.float32)
    mask = np.ones(n, np.float32)
    mask[n_raw:] = 0.0
    return (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask), y, n)


def _grow(leaves, wave, endgame, impl="pallas", quantized=False):
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                     any_cat=False)
    return make_wave_grow_fn(
        num_leaves=leaves, num_features=F, max_bins=B, max_depth=0,
        split_params=sp, hist_impl=impl, any_cat=False, interpret=True,
        jit=False, wave_size=wave, quantized=quantized, stochastic=False,
        spec_ramp=False, exact_endgame=endgame)


def _call(grow, bins, grad, hess, mask):
    nb = jnp.full((F,), B, jnp.int32)
    return grow(bins, grad, hess, mask, nb,
                jnp.zeros((F,), bool), jnp.zeros((F,), bool),
                jnp.zeros((F,), jnp.int32), jnp.zeros((F,), jnp.float32),
                (), jnp.ones((F,), bool))


def _assert_same_tree(a, b, atol=0.0):
    assert int(a.num_leaves) == int(b.num_leaves)
    for name in ("split_feature", "threshold_bin", "nan_bin",
                 "decision_type", "left_child", "right_child"):
        np.testing.assert_array_equal(np.asarray(getattr(a, name)),
                                      np.asarray(getattr(b, name)), err_msg=name)
    np.testing.assert_array_equal(np.asarray(a.row_leaf),
                                  np.asarray(b.row_leaf))
    np.testing.assert_allclose(np.asarray(a.leaf_value),
                               np.asarray(b.leaf_value), rtol=0, atol=atol)


def test_wave1_endgame_bitwise_matches_sequential():
    """wave_size=1 is the exact sequential grower; flipping the endgame on
    must not change a single bit of the golden-fixture tree."""
    bins, grad, hess, mask, y, n = _mk_data()
    t_off = _call(_grow(13, 1, False), bins, grad, hess, mask)
    t_on = _call(_grow(13, 1, True), bins, grad, hess, mask)
    _assert_same_tree(t_off, t_on)
    np.testing.assert_array_equal(np.asarray(t_off.split_gain),
                                  np.asarray(t_on.split_gain))


def test_full_endgame_reproduces_sequential_order():
    """num_leaves-1 < 2W puts every split in the endgame: the tree must be
    bitwise identical to the wave_size=1 sequential tree."""
    bins, grad, hess, mask, y, n = _mk_data(seed=2)
    t_seq = _call(_grow(13, 1, False), bins, grad, hess, mask)
    t_eg = _call(_grow(13, 8, True), bins, grad, hess, mask)
    _assert_same_tree(t_seq, t_eg)


def test_endgame_quantized_matches_sequential():
    bins, grad, hess, mask, y, n = _mk_data(seed=3)
    t_seq = _call(_grow(13, 1, False, quantized=True), bins, grad, hess,
                  mask)
    t_eg = _call(_grow(13, 8, True, quantized=True), bins, grad, hess,
                 mask)
    _assert_same_tree(t_seq, t_eg)


def test_endgame_xla_path_matches_pallas():
    """The onehot (non-Pallas) trial-channel / row-update fallback must
    produce the same tree as the fused kernels."""
    bins, grad, hess, mask, y, n = _mk_data(seed=4)
    t_pl = _call(_grow(13, 8, True, impl="pallas"), bins, grad, hess, mask)
    t_oh = _call(_grow(13, 8, True, impl="onehot"), bins, grad, hess, mask)
    _assert_same_tree(t_pl, t_oh, atol=1e-6)


def test_endgame_saves_passes_vs_taper():
    """hist_passes: the endgame must not spend more full-data passes than
    the taper, and must report the counter at all."""
    bins, grad, hess, mask, y, n = _mk_data(seed=5)
    t_taper = _call(_grow(13, 4, False), bins, grad, hess, mask)
    t_eg = _call(_grow(13, 4, True), bins, grad, hess, mask)
    p_taper, p_eg = int(t_taper.hist_passes), int(t_eg.hist_passes)
    assert p_taper >= 3                      # root + waves + taper
    assert p_eg <= p_taper
    assert int(t_eg.num_leaves) == int(t_taper.num_leaves) == 13


def test_endgame_heldout_quality_vs_taper():
    """The endgame reproduces the exact order where the taper
    approximates it — held-out loss must be at least taper-par."""
    bins, grad, hess, mask, y, n = _mk_data(n_raw=8000, seed=6)
    ho_bins, ho_grad, ho_hess, ho_mask, ho_y, _ = _mk_data(n_raw=8000,
                                                           seed=7)

    def heldout_loss(tree):
        # route the held-out rows through the grown tree's binned splits
        sf = np.asarray(tree.split_feature)
        thr = np.asarray(tree.threshold_bin)
        lc = np.asarray(tree.left_child)
        rc = np.asarray(tree.right_child)
        lv = np.asarray(tree.leaf_value)
        Xb = np.asarray(ho_bins)
        m = np.asarray(ho_mask) > 0
        preds = np.zeros(Xb.shape[1])
        for i in range(Xb.shape[1]):
            node = 0
            while True:
                f_, t_ = sf[node], thr[node]
                nxt = lc[node] if Xb[f_, i] <= t_ else rc[node]
                if nxt < 0:
                    preds[i] = lv[-(nxt + 1)]
                    break
                node = nxt
        p = 1.0 / (1.0 + np.exp(-4.0 * preds))
        p = np.clip(p, 1e-6, 1 - 1e-6)
        return -np.mean(ho_y[m] * np.log(p[m]) +
                        (1 - ho_y[m]) * np.log(1 - p[m]))

    t_taper = _call(_grow(13, 4, False), bins, grad, hess, mask)
    t_eg = _call(_grow(13, 4, True), bins, grad, hess, mask)
    ll_taper = heldout_loss(t_taper)
    ll_eg = heldout_loss(t_eg)
    assert ll_eg < ll_taper * 1.02 + 1e-3


def test_cegb_lazy_bitpack_matches_bool():
    """Satellite: the packed uint8 lazy-CEGB bitmap must reproduce the
    bool path bit-for-bit (same trees, same persistent bitmap)."""
    bins, grad, hess, mask, y, n = _mk_data(seed=8)
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                     any_cat=False)

    def grow_lazy(bitpack):
        return make_wave_grow_fn(
            num_leaves=9, num_features=F, max_bins=B, max_depth=0,
            split_params=sp, hist_impl="pallas", any_cat=False,
            interpret=True, jit=False, wave_size=4,
            cegb_lazy=(0.01,) * F, exact_endgame=False,
            lazy_bitpack=bitpack)

    t_p, used_p = _call(grow_lazy(True), bins, grad, hess, mask)
    t_b, used_b = _call(grow_lazy(False), bins, grad, hess, mask)
    _assert_same_tree(t_p, t_b)
    assert used_p.dtype == jnp.uint8 and used_b.dtype == jnp.bool_
    assert used_p.shape == (F, n // 8)
    from lightgbm_tpu.learner.wave import _unpack_bits
    np.testing.assert_array_equal(np.asarray(_unpack_bits(used_p)),
                                  np.asarray(used_b))
