"""EFB (exclusive feature bundling) + sparse ingestion tests (reference:
dataset.cpp:53-353 FindGroups/FastFeatureBundling; verdict round-2 bar:
a wide 99%-sparse synthetic trains with device width ~ bundle count and
matches unbundled predictions)."""

import numpy as np
import scipy.sparse as sp

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config


def _sparse_data(n=3000, f=60, seed=0, density=0.02):
    """Wide sparse one-hot-ish features + 2 dense informative columns."""
    rng = np.random.RandomState(seed)
    X = np.zeros((n, f))
    X[:, 0] = rng.randn(n)
    X[:, 1] = rng.randn(n)
    for j in range(2, f):
        rows = rng.choice(n, size=max(1, int(n * density)), replace=False)
        X[rows, j] = rng.rand(len(rows)) * 2 + 0.5
    y = (X[:, 0] + 0.5 * X[:, 1] + 2.0 * (X[:, 7] > 0) - (X[:, 11] > 0)
         + 0.1 * rng.randn(n))
    return X, y


P = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
     "metric": "l2", "min_data_in_leaf": 5}


def test_bundles_shrink_device_width():
    X, y = _sparse_data()
    ds = lgb.Dataset(X, y, params=P)
    ds.construct(Config(P))
    assert ds.efb is not None
    assert ds.X_binned.shape[1] == ds.efb.n_bundles
    # 58 sparse columns collapse to the 255-bundle-bin capacity limit
    assert ds.efb.n_bundles < 25


def test_bundled_matches_unbundled_predictions():
    X, y = _sparse_data()
    b_on = lgb.train(P, lgb.Dataset(X, y), 10)
    b_off = lgb.train({**P, "enable_bundle": False}, lgb.Dataset(X, y), 10)
    assert b_on._gbdt.train_set.efb is not None
    assert b_off._gbdt.train_set.efb is None
    np.testing.assert_allclose(b_on.predict(X), b_off.predict(X),
                               rtol=1e-4, atol=1e-5)


def test_sparse_csr_input_no_densify():
    X, y = _sparse_data()
    Xs = sp.csr_matrix(X)
    bst = lgb.train(P, lgb.Dataset(Xs, y), 15)
    dense = lgb.train(P, lgb.Dataset(X, y), 15)
    # same binning from sparse vs dense ingestion -> same predictions
    np.testing.assert_allclose(bst.predict(X), dense.predict(X),
                               rtol=1e-3, atol=1e-3)
    mse = np.mean((bst.predict(X) - y) ** 2)
    assert mse < np.var(y) * 0.3


def test_wide_sparse_trains():
    """10k-feature 99%-sparse synthetic (the verdict's acceptance bar)."""
    rng = np.random.RandomState(3)
    n, f = 3000, 10000
    nnz_per_row = 40
    rows = np.repeat(np.arange(n), nnz_per_row)
    cols = rng.randint(0, f, n * nnz_per_row)
    vals = rng.rand(n * nnz_per_row) + 0.5
    Xs = sp.csr_matrix((vals, (rows, cols)), shape=(n, f))
    w = np.zeros(f)
    w[:50] = rng.randn(50)
    y = np.asarray(Xs[:, :50] @ w[:50]).ravel() + 0.1 * rng.randn(n)
    ds = lgb.Dataset(Xs, y, params=P)
    bst = lgb.train({**P, "num_leaves": 31}, ds, 5)
    efb = bst._gbdt.train_set.efb
    assert efb is not None
    width = bst._gbdt.train_set.X_binned.shape[1]
    assert width == efb.n_bundles
    assert width < f / 10  # 10k features in <1k device columns
    # quality bar on a fixed slice (densifying all 3000x10000 rows just
    # to score them dominated this test's runtime on 1 core)
    sl = slice(0, 1000)
    mse = np.mean((bst.predict(np.asarray(Xs[sl].todense())) - y[sl]) ** 2)
    assert mse < np.var(y[sl]) * 0.6


def test_efb_model_io_roundtrip(tmp_path):
    X, y = _sparse_data()
    bst = lgb.train(P, lgb.Dataset(X, y), 10)
    assert bst._gbdt.train_set.efb is not None
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(loaded.predict(X), bst.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_efb_valid_set_trains():
    X, y = _sparse_data()
    ds = lgb.Dataset(X, y)
    bst = lgb.train(P, ds, 5, valid_sets=[lgb.Dataset(X, y, reference=ds)])
    assert np.isfinite(bst.predict(X)).all()


def test_efb_valid_sets_match_direct_prediction():
    """Valid-set eval on an EFB-bundled reference must equal metrics
    computed from direct raw-row prediction (the bundle-space tree walk,
    models/tree.py _walk_binned_efb)."""
    X, y = _sparse_data(seed=11)
    ds = lgb.Dataset(sp.csr_matrix(X), y, params=P)
    vs = lgb.Dataset(sp.csr_matrix(X[:600]), y[:600], reference=ds)
    ev = {}
    bst = lgb.train(P, ds, num_boost_round=8, valid_sets=[vs],
                    valid_names=["v"],
                    callbacks=[lgb.record_evaluation(ev)])
    got = ev["v"]["l2"][-1]
    ref = float(np.mean((bst.predict(X[:600]) - y[:600]) ** 2))
    assert abs(got - ref) < 1e-4 * max(1.0, abs(ref))


def test_efb_continued_training_and_rollback(tmp_path):
    """Score rebuilds on the bundle-space matrix: init_model resumes from
    a saved EFB-trained model and keeps improving."""
    X, y = _sparse_data(seed=12)
    ds = lgb.Dataset(sp.csr_matrix(X), y, params=P)
    bst = lgb.train(P, ds, num_boost_round=10)
    path = str(tmp_path / "efb.txt")
    bst.save_model(path)
    ds2 = lgb.Dataset(sp.csr_matrix(X), y, params=P)
    bst2 = lgb.train(P, ds2, num_boost_round=10, init_model=path)
    m1 = float(np.mean((bst.predict(X) - y) ** 2))
    m2 = float(np.mean((bst2.predict(X) - y) ** 2))
    assert np.isfinite(m2) and m2 <= m1 + 1e-6
