"""Config system tests (reference param surface: config.h + config_auto.cpp
alias table; analog of parts of tests/python_package_test/test_basic.py)."""

import pytest

from lightgbm_tpu.config import Config, parse_config_file


def test_defaults():
    c = Config()
    assert c.num_leaves == 31
    assert c.learning_rate == 0.1
    assert c.max_bin == 255
    assert c.objective == "regression"
    assert c.boosting == "gbdt"
    assert c.tree_learner == "serial"


def test_aliases():
    c = Config({"num_leaf": 64, "eta": 0.3, "application": "binary",
                "sub_row": 0.5, "min_child_samples": 7, "nthread": 4})
    assert c.num_leaves == 64
    assert c.learning_rate == 0.3
    assert c.objective == "binary"
    assert c.bagging_fraction == 0.5
    assert c.min_data_in_leaf == 7
    assert c.num_threads == 4


def test_objective_aliases():
    assert Config({"objective": "mse"}).objective == "regression"
    assert Config({"objective": "mae"}).objective == "regression_l1"
    assert Config({"objective": "softmax", "num_class": 3}).objective == "multiclass"
    assert Config({"objective": "xentropy"}).objective == "cross_entropy"
    assert Config({"objective": "xendcg"}).objective == "rank_xendcg"
    assert Config({"boosting": "gbrt"}).boosting == "gbdt"
    assert Config({"tree_learner": "data_parallel"}).tree_learner == "data"


def test_validation():
    with pytest.raises(ValueError):
        Config({"num_leaves": 1})
    with pytest.raises(ValueError):
        Config({"bagging_fraction": 0.0})
    with pytest.raises(ValueError):
        Config({"force_col_wise": True, "force_row_wise": True})
    with pytest.raises(ValueError):
        Config({"objective": "multiclass", "num_class": 1})
    with pytest.raises(ValueError):
        Config({"top_rate": 0.8, "other_rate": 0.5})


def test_string_coercion():
    c = Config({"num_leaves": "15", "learning_rate": "0.05",
                "feature_pre_filter": "false", "metric": "l2,auc"})
    assert c.num_leaves == 15
    assert c.learning_rate == 0.05
    assert c.feature_pre_filter is False
    assert c.metric == ["l2", "auc"]


def test_unknown_params_kept():
    c = Config({"my_custom_thing": 5})
    assert c.extra["my_custom_thing"] == 5


def test_update_returns_new():
    c = Config({"num_leaves": 15})
    c2 = c.update({"num_leaves": 31})
    assert c.num_leaves == 15 and c2.num_leaves == 31


def test_seed_cascade():
    c = Config({"seed": 77})
    c2 = Config({"seed": 77})
    assert c.bagging_seed == c2.bagging_seed
    assert c.bagging_seed != Config({"seed": 78}).bagging_seed


def test_config_file(tmp_path):
    p = tmp_path / "train.conf"
    p.write_text("# comment\ntask = train\nnum_leaves = 63\n"
                 "metric = binary_logloss,auc\n")
    params = parse_config_file(str(p))
    c = Config(params)
    assert c.num_leaves == 63
    assert c.metric == ["binary_logloss", "auc"]
