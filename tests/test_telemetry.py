"""Telemetry subsystem tests (lightgbm_tpu/telemetry/).

Covers: the metrics registry primitives and their thread-safety, the
shared percentile/sliding-window implementation serve/stats now rides
on, span tracing + chrome export, the timer satellites (log routing,
registry publish, debug-strict stop), TrainRecord accumulation through
real training, the bit-identical-training contract, the trace-time
collective tally against the jaxpr psum count (the same quantity
tests/test_specramp.py asserts), Prometheus rendering, the /metrics
endpoint end-to-end, the profile CLI verb, and the enabled-vs-disabled
overhead guard.
"""

import json
import math
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu.telemetry.metrics import (MetricsRegistry, SlidingWindow,
                                            percentile)

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


@pytest.fixture(autouse=True)
def _telemetry_enabled():
    """Tests assume the default-on switch; restore whatever state the
    process was in afterwards."""
    was = telemetry.enabled()
    telemetry.enable()
    yield
    (telemetry.enable if was else telemetry.disable)()


def _train_binary(n=400, trees=5, seed=0, extra=None):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, 6)
    y = (X[:, 0] * 2 + X[:, 1] + 0.5 * rng.randn(n) > 0).astype(np.float64)
    p = {**SMALL, "objective": "binary", **(extra or {})}
    return lgb.train(p, lgb.Dataset(X, y, params=p), trees), X


# -- metrics primitives -----------------------------------------------------

def test_counter_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("c", "help", labels=("who",))
    c.inc(2, who="a")
    c.inc(who="a")
    c.inc(who="b")
    assert c.value(who="a") == 3 and c.value(who="b") == 1
    g = reg.gauge("g")
    g.set(5)
    g.max(3)       # watermark keeps the larger value
    assert g.value() == 5
    g.max(9)
    assert g.value() == 9
    assert reg.counter("c", labels=("who",)) is c  # get-or-create


def test_registry_kind_conflict_raises():
    reg = MetricsRegistry()
    reg.counter("x", labels=("a",))
    with pytest.raises(ValueError):
        reg.gauge("x")
    with pytest.raises(ValueError):
        reg.counter("x", labels=("b",))


def test_label_validation():
    reg = MetricsRegistry()
    c = reg.counter("c", labels=("model",))
    with pytest.raises(ValueError):
        c.inc(1)  # missing label
    with pytest.raises(ValueError):
        c.inc(1, model="m", extra="nope")


def test_sliding_window_wrap_and_percentile():
    w = SlidingWindow(capacity=8)
    for v in range(20):
        w.add(float(v))
    assert len(w) == 8
    assert w.count == 20 and w.total == sum(range(20))
    assert w.sorted_values() == [float(v) for v in range(12, 20)]
    assert w.percentile(0) == 12.0 and w.percentile(100) == 19.0
    # nearest-rank edge cases of the shared helper
    assert percentile([], 50) == 0.0
    assert percentile([3.0], 99) == 3.0


def test_percentile_is_shared_with_serve_stats():
    from lightgbm_tpu.serve import stats as serve_stats
    assert serve_stats.percentile is percentile


def test_registry_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("hits", labels=("t",))
    h = reg.histogram("lat", labels=("t",), window=64)
    n_threads, n_ops = 8, 500
    barrier = threading.Barrier(n_threads)

    def worker(t):
        barrier.wait()
        for i in range(n_ops):
            c.inc(1, t=str(t % 2))
            h.observe(float(i), t=str(t % 2))

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(t="0") + c.value(t="1") == n_threads * n_ops
    total = sum(summ["count"] for _, summ in h.series())
    assert total == n_threads * n_ops


def test_model_stats_schema_unchanged():
    """ModelStats rebased on the registry must keep its /stats schema."""
    from lightgbm_tpu.serve.stats import ModelStats
    s = ModelStats()
    s.record_request(3)
    s.record_batch(3, 8, 1.5, recompiled=True)
    s.record_batch(5, 8, 2.5, recompiled=False)
    s.record_error()
    snap = s.snapshot()
    assert snap["requests"] == 1 and snap["rows"] == 8
    assert snap["batches"] == 2 and snap["recompiles"] == 1
    assert snap["errors"] == 1
    assert snap["bucket_histogram"] == {"8": 2}
    assert snap["latency_ms"]["window"] == 2
    assert snap["latency_ms"]["p50"] > 0
    # two anonymous ModelStats never alias each other's series
    s2 = ModelStats()
    assert s2.snapshot()["batches"] == 0


# -- spans ------------------------------------------------------------------

def test_span_disabled_is_shared_noop():
    from lightgbm_tpu.telemetry import trace as ttrace
    assert not ttrace.global_tracer.enabled
    a = telemetry.span("x")
    b = telemetry.span("y")
    assert a is b  # the shared no-op instance


def test_span_nesting_and_chrome_export(tmp_path):
    tr = telemetry.global_tracer
    tr.enable()
    tr.clear()
    try:
        with telemetry.span("tree"):
            with telemetry.span("wave"):
                time.sleep(0.002)
            with telemetry.span("psum"):
                pass
        names = [e["name"] for e in tr.events()]
        assert names == ["tree/wave", "tree/psum", "tree"]
        assert all(e["ph"] == "X" and e["dur"] >= 0 for e in tr.events())
        out = tmp_path / "trace.json"
        assert tr.export_chrome_trace(str(out)) == 3
        doc = json.loads(out.read_text())
        assert len(doc["traceEvents"]) == 3
    finally:
        tr.disable()
        tr.clear()


# -- timer satellites -------------------------------------------------------

def test_timer_stop_without_start_raises_in_debug():
    from lightgbm_tpu.utils.log import (LEVEL_DEBUG, get_verbosity,
                                        set_verbosity)
    from lightgbm_tpu.utils.timer import Timer
    t = Timer()
    t.enable()
    old = get_verbosity()
    try:
        set_verbosity(0)
        t.stop("never-started")  # silent below debug
        set_verbosity(LEVEL_DEBUG)
        with pytest.raises(RuntimeError, match="without a matching start"):
            t.stop("never-started")
    finally:
        set_verbosity(old)


def test_timer_exit_report_routes_through_log():
    """The exit report goes through the log sink (callbacks capture it)
    but is NOT verbosity-filtered — training configs routinely set
    verbosity=-1 and an explicitly enabled timetag must still report."""
    from lightgbm_tpu.utils import log
    from lightgbm_tpu.utils.timer import Timer
    t = Timer()
    t.enable()
    t.start("phase")
    t.stop("phase")
    lines = []
    old_v = log.get_verbosity()
    log.register_log_callback(lines.append)
    try:
        log.set_verbosity(-1)
        t.print_at_exit()
    finally:
        log.set_verbosity(old_v)
        log.register_log_callback(None)
    assert any("time tags" in l and "phase" in l for l in lines)


def test_timer_publishes_to_registry():
    from lightgbm_tpu.utils.timer import Timer
    t = Timer()
    t.enable()
    t.start("probe_tag")
    t.stop("probe_tag")
    reg = telemetry.default_registry()
    assert reg.counter("timetag_calls_total",
                       labels=("tag",)).value(tag="probe_tag") >= 1
    assert reg.counter("timetag_seconds_total",
                       labels=("tag",)).value(tag="probe_tag") >= 0


# -- TrainRecord through real training --------------------------------------

def test_train_record_accumulates():
    bst, _ = _train_binary(trees=5)
    rec = bst.train_record
    assert rec is telemetry.last_train_record()
    snap = rec.snapshot()
    assert snap["schema"] == "train-record-v1"
    assert snap["num_trees"] == 5
    assert len(snap["trees"]) == 5
    assert [r["iteration"] for r in snap["trees"]] == list(range(5))
    for ph in ("gradients", "grow", "record"):
        assert snap["phase_seconds"].get(ph, 0) > 0
        assert snap["phase_calls"][ph] == 5
    assert snap["meta"]["objective"] == "binary"
    assert all(r["num_leaves"] >= 1 for r in snap["trees"])


def test_train_record_wave_hist_passes():
    """Through the full Booster path on the wave grower, the exported
    per-tree hist_passes must equal the GrownTree counter the endgame
    tests assert (gbdt.last_hist_passes is the last tree's)."""
    bst, _ = _train_binary(n=600, trees=3,
                           extra={"tree_grow_mode": "wave",
                                  "num_leaves": 13})
    snap = bst.train_record.snapshot()
    hp = [r["hist_passes"] for r in snap["trees"]]
    assert len(hp) == 3
    assert all(p >= 1 for p in hp), hp  # wave grower tracks passes
    assert hp[-1] == int(bst._gbdt.last_hist_passes)
    assert snap["hist_passes_total"] == sum(hp)
    assert snap["hist_passes_last"] == hp[-1]


def test_training_bit_identical_with_telemetry_disabled():
    """The acceptance contract: telemetry only observes — the grown
    model must be bit-for-bit the same with telemetry on and off."""
    telemetry.disable()
    try:
        bst_off, X = _train_binary(trees=4, seed=3)
        txt_off = bst_off.model_to_string()
        pred_off = bst_off.predict(X[:50], raw_score=True)
    finally:
        telemetry.enable()
    bst_on, X2 = _train_binary(trees=4, seed=3)
    assert bst_on.model_to_string() == txt_off
    np.testing.assert_array_equal(
        bst_on.predict(X2[:50], raw_score=True), pred_off)
    # and the disabled run recorded nothing
    assert bst_off.train_record.snapshot()["num_trees"] == 0
    assert bst_on.train_record.snapshot()["num_trees"] == 4


# -- collective tally vs the traced program ---------------------------------

def _mk_dp_data(n_raw):
    from lightgbm_tpu.ops.histogram_pallas import pad_rows
    rng = np.random.RandomState(0)
    n = pad_rows(n_raw)
    bins = rng.randint(0, 63, (6, n)).astype(np.uint8)
    y = ((bins[0] > 30).astype(np.float32))
    grad = (0.5 - y).astype(np.float32)
    hess = np.full(n, 0.25, np.float32)
    mask = np.ones(n, np.float32)
    mask[n_raw:] = 0.0
    return bins, grad, hess, mask, n


def _trace_dp_grow(spec, wave=4):
    """Trace (don't run) the DP wave grower; the psum counting rides the
    shared analysis.ir walker (tests/test_specramp.py counts the same
    quantity through the same API)."""
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.analysis import ir
    from jax.sharding import PartitionSpec as P
    from lightgbm_tpu.learner.wave import make_wave_grow_fn
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.parallel.data_parallel import (DataParallelTreeLearner,
                                                     WaveDPStrategy)
    from lightgbm_tpu.parallel.mesh import get_mesh, shard_map_compat
    mesh = get_mesh(8)
    ax = mesh.axis_names[0]
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                     any_cat=False)
    grow = make_wave_grow_fn(
        num_leaves=13, num_features=6, max_bins=64, max_depth=0,
        split_params=sp, hist_impl="pallas", any_cat=False, interpret=True,
        jit=False, wave_size=wave, quantized=True, stochastic=False,
        spec_ramp=spec, spec_tol=0.02,
        strategy=WaveDPStrategy(ax, nshards=8))
    wrapped = jax.jit(shard_map_compat(
        lambda X_T, g, h, m, nb, ic, hn, mono, cp, fm: grow(
            X_T, g, h, m, nb, ic, hn, mono, cp, (), fm),
        mesh=mesh,
        in_specs=(P(None, ax), P(ax), P(ax), P(ax), P(), P(), P(), P(),
                  P(), P()),
        out_specs=DataParallelTreeLearner._tree_specs(ax)))
    bins, grad, hess, mask, n = _mk_dp_data(8 * 4096 - 100)
    nb = jnp.full((6,), 64, jnp.int32)
    args = (jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
            jnp.asarray(mask), nb, jnp.zeros((6,), bool),
            jnp.zeros((6,), bool), jnp.zeros((6,), jnp.int32),
            jnp.zeros((6,), jnp.float32), jnp.ones((6,), bool))
    before = telemetry.collectives_snapshot().get(
        "data_parallel/wave/hist_psum", {"count": 0})["count"]
    n_psum = ir.count_primitive(
        ir.trace(lambda *a: wrapped(*a), *args), "psum")
    after = telemetry.collectives_snapshot().get(
        "data_parallel/wave/hist_psum", {"count": 0})["count"]
    return after - before, n_psum


def test_collective_tally_matches_traced_psum_delta():
    """The telemetry tally at the WaveDPStrategy.reduce_hist site must
    report the SAME spec-ramp collective budget test_specramp.py asserts
    on the jaxpr: spec-on minus spec-off == ceil(log2(W)) extra
    histogram psums per tree."""
    w = 4
    tally_off, n_off = _trace_dp_grow(False, wave=w)
    tally_on, n_on = _trace_dp_grow(True, wave=w)
    assert tally_off >= 1
    assert tally_on - tally_off == math.ceil(math.log2(w))
    # the tally site is the histogram psum: its per-trace count moves
    # exactly with the program's psum op count
    assert (tally_on - tally_off) == (n_on - n_off)
    # and the recorded bytes are the histogram batch operand size
    rec = telemetry.collectives_snapshot()["data_parallel/wave/hist_psum"]
    assert rec["op"] == "psum" and rec["bytes"] > 0


# -- export + /metrics ------------------------------------------------------

def test_prometheus_render_covers_registry_and_train_record():
    bst, X = _train_binary(trees=3, seed=5)
    txt = telemetry.render_prometheus()
    assert "# TYPE lgbm_tpu_train_trees_total counter" in txt
    assert "lgbm_tpu_train_trees_total 3" in txt
    assert 'lgbm_tpu_train_phase_seconds_total{phase="grow"}' in txt
    doc = telemetry.render_json()
    assert doc["schema"] == "telemetry-snapshot-v1"
    assert doc["train_record"]["num_trees"] == 3


def test_metrics_endpoint_e2e():
    """Acceptance: /metrics serves Prometheus text covering both the
    serving counters and the last training run's TrainRecord."""
    import http.client
    from lightgbm_tpu.serve import ModelRegistry, PredictionServer
    bst, X = _train_binary(trees=4, seed=7)
    reg = ModelRegistry()
    reg.load("telem_model", bst, warmup=False)
    srv = PredictionServer(reg, port=0, batching=False).start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port,
                                          timeout=60)
        conn.request("POST", "/predict",
                     json.dumps({"rows": X[:3].tolist()}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 200
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Content-Type").startswith("text/plain")
        body = resp.read().decode()
        # serving counters, labeled by model
        assert 'lgbm_tpu_serve_requests_total{model="telem_model"} 1' \
            in body
        assert 'lgbm_tpu_serve_rows_total{model="telem_model"} 3' in body
        assert 'lgbm_tpu_serve_batch_latency_ms_p50' \
               '{model="telem_model"}' in body
        # the last training run's record
        assert "lgbm_tpu_train_trees_total 4" in body
        assert 'lgbm_tpu_train_phase_seconds_total{phase="grow"}' in body
    finally:
        srv.shutdown()


def test_profile_cli_verb(tmp_path):
    """`python -m lightgbm_tpu profile` trains, then dumps telemetry +
    host spans (device capture disabled for speed)."""
    from lightgbm_tpu.cli import main as cli_main
    rng = np.random.RandomState(0)
    X = rng.randn(200, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    data = tmp_path / "train.csv"
    np.savetxt(data, np.column_stack([y, X]), delimiter=",", fmt="%.6f")
    prof = tmp_path / "prof"
    rc = cli_main([
        "profile", f"data={data}", "task=train", "objective=binary",
        "num_leaves=4", "min_data_in_leaf=5", "num_iterations=3",
        "header=false", "verbosity=-1",
        f"output_model={tmp_path / 'model.txt'}",
        f"profile_dir={prof}", "jax_trace=0",
    ])
    assert rc == 0
    # the verb enables the tracer/timer process-wide; undo for the rest
    # of the suite
    from lightgbm_tpu.utils.timer import global_timer
    telemetry.global_tracer.disable()
    telemetry.global_tracer.clear()
    global_timer.enabled = False
    dump = json.loads((prof / "telemetry.json").read_text())
    assert dump["schema"] == "telemetry-snapshot-v1"
    assert dump["train_record"]["num_trees"] == 3
    spans = json.loads((prof / "host_spans.json").read_text())
    assert any(e["name"].startswith("train/")
               for e in spans["traceEvents"])
    assert (tmp_path / "model.txt").exists()


# -- overhead guard ---------------------------------------------------------

def test_telemetry_overhead_guard():
    """CI satellite: telemetry-enabled training must stay within a
    generous wall-time ratio of disabled training (it only appends to
    host-side lists and reads perf_counter)."""
    def timed(trees=6, seed=11):
        t0 = time.perf_counter()
        _train_binary(n=1000, trees=trees, seed=seed)
        return time.perf_counter() - t0

    timed(trees=2)          # warm compile caches out of the measurement
    telemetry.disable()
    try:
        t_off = timed()
    finally:
        telemetry.enable()
    t_on = timed()
    # generous: the accumulation is micro-seconds per tree; anything
    # near the ratio would be a real regression, not timing noise
    assert t_on <= 3.0 * t_off + 1.0, (t_on, t_off)
