"""Behavioral training tests (analog of reference
tests/python_package_test/test_engine.py — per-objective quality thresholds,
early stopping, cv, boosting variants, missing/categorical semantics)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


def _auc(y, p):
    order = np.argsort(-p)
    y = y[order]
    tp = np.cumsum(y)
    fp = np.cumsum(1 - y)
    if tp[-1] == 0 or fp[-1] == 0:
        return 0.5
    return float(np.trapz(tp, fp) / (tp[-1] * fp[-1]))


def test_binary(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary"}, lgb.Dataset(X, y), 20)
    p = bst.predict(X)
    assert _auc(y, p) > 0.95
    assert ((p >= 0) & (p <= 1)).all()


def test_regression(regression_data):
    X, y = regression_data
    bst = lgb.train({**SMALL, "objective": "regression"}, lgb.Dataset(X, y), 25)
    p = bst.predict(X)
    assert np.mean((p - y) ** 2) < 0.3 * np.var(y)


def test_regression_l1(regression_data):
    X, y = regression_data
    bst = lgb.train({**SMALL, "objective": "regression_l1",
                     "learning_rate": 0.2}, lgb.Dataset(X, y), 25)
    p = bst.predict(X)
    assert np.mean(np.abs(p - y)) < 0.6 * np.mean(np.abs(y - np.median(y)))


@pytest.mark.parametrize("objective", ["huber", "fair", "quantile", "mape"])
def test_robust_regression_objectives(objective, regression_data):
    X, y = regression_data
    y_pos = y - y.min() + 1.0
    bst = lgb.train({**SMALL, "objective": objective, "learning_rate": 0.2},
                    lgb.Dataset(X, y_pos), 15)
    p = bst.predict(X)
    assert np.isfinite(p).all()
    assert np.mean((p - y_pos) ** 2) < np.var(y_pos)


@pytest.mark.parametrize("objective", ["poisson", "gamma", "tweedie"])
def test_positive_regression_objectives(objective, regression_data):
    X, y = regression_data
    y_pos = np.exp(y / max(1.0, np.abs(y).max()) * 2)  # positive target
    bst = lgb.train({**SMALL, "objective": objective, "learning_rate": 0.2},
                    lgb.Dataset(X, y_pos), 15)
    p = bst.predict(X)
    assert np.isfinite(p).all()
    assert (p > 0).all()  # log-link: outputs are means


def test_multiclass(multiclass_data):
    X, y = multiclass_data
    bst = lgb.train({**SMALL, "objective": "multiclass", "num_class": 3},
                    lgb.Dataset(X, y), 15)
    p = bst.predict(X)
    assert p.shape == (len(y), 3)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    assert (p.argmax(axis=1) == y).mean() > 0.9


def test_multiclassova(multiclass_data):
    X, y = multiclass_data
    bst = lgb.train({**SMALL, "objective": "multiclassova", "num_class": 3},
                    lgb.Dataset(X, y), 15)
    p = bst.predict(X)
    assert p.shape == (len(y), 3)
    assert (p.argmax(axis=1) == y).mean() > 0.9


def test_cross_entropy(binary_data):
    X, y = binary_data
    # probabilistic labels
    yl = np.clip(y * 0.9 + 0.05, 0, 1)
    bst = lgb.train({**SMALL, "objective": "cross_entropy"},
                    lgb.Dataset(X, yl), 15)
    p = bst.predict(X)
    assert ((p >= 0) & (p <= 1)).all()
    assert _auc(y, p) > 0.9


def test_lambdarank(rank_data):
    X, y, group = rank_data
    bst = lgb.train({**SMALL, "objective": "lambdarank", "metric": "ndcg",
                     "eval_at": [5], "learning_rate": 0.2},
                    lgb.Dataset(X, y, group=group), 15)
    p = bst.predict(X)
    # predicted order should correlate with labels
    assert np.corrcoef(p, y)[0, 1] > 0.5


def test_rank_xendcg(rank_data):
    X, y, group = rank_data
    bst = lgb.train({**SMALL, "objective": "rank_xendcg",
                     "learning_rate": 0.2}, lgb.Dataset(X, y, group=group), 15)
    p = bst.predict(X)
    assert np.corrcoef(p, y)[0, 1] > 0.4


def test_early_stopping():
    rng = np.random.RandomState(0)
    # small, noisy data + aggressive lr -> certain overfit on the valid set
    X = rng.randn(200, 5)
    y = X[:, 0] + 1.5 * rng.randn(200)
    ds = lgb.Dataset(X[:120], y[:120])
    vs = ds.create_valid(X[120:], y[120:])
    bst = lgb.train({**SMALL, "objective": "regression", "metric": "l2",
                     "learning_rate": 0.5, "min_data_in_leaf": 2,
                     "early_stopping_round": 5}, ds, 100, valid_sets=[vs])
    assert 0 < bst.best_iteration < 100


def test_eval_result_recording(regression_data):
    X, y = regression_data
    ds = lgb.Dataset(X[:400], y[:400])
    vs = ds.create_valid(X[400:], y[400:])
    hist = {}
    lgb.train({**SMALL, "objective": "regression", "metric": ["l2", "l1"]},
              ds, 8, valid_sets=[vs],
              callbacks=[lgb.record_evaluation(hist)])
    assert "valid_0" in hist
    assert len(hist["valid_0"]["l2"]) == 8
    assert hist["valid_0"]["l2"][-1] <= hist["valid_0"]["l2"][0]


def test_weights(binary_data):
    X, y = binary_data
    w = np.where(y > 0, 2.0, 1.0)
    bst = lgb.train({**SMALL, "objective": "binary"},
                    lgb.Dataset(X, y, weight=w), 10)
    p = bst.predict(X)
    assert _auc(y, p) > 0.9


def test_bagging(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary", "bagging_freq": 1,
                     "bagging_fraction": 0.6}, lgb.Dataset(X, y), 15)
    assert _auc(y, bst.predict(X)) > 0.9


def test_feature_fraction(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary", "feature_fraction": 0.5},
                    lgb.Dataset(X, y), 15)
    assert _auc(y, bst.predict(X)) > 0.9


def test_goss(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary", "boosting": "goss",
                     "learning_rate": 0.3}, lgb.Dataset(X, y), 15)
    assert _auc(y, bst.predict(X)) > 0.9


def test_dart(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary", "boosting": "dart",
                     "drop_rate": 0.3}, lgb.Dataset(X, y), 15)
    assert _auc(y, bst.predict(X)) > 0.9


def test_rf(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary", "boosting": "rf",
                     "bagging_freq": 1, "bagging_fraction": 0.7},
                    lgb.Dataset(X, y), 10)
    p = bst.predict(X)
    assert _auc(y, p) > 0.85
    assert ((p >= 0) & (p <= 1)).all()


def test_custom_objective(regression_data):
    X, y = regression_data

    def fobj(preds, dataset):
        label = dataset.get_label()
        return preds - label, np.ones_like(preds)

    def feval(preds, dataset):
        label = dataset.get_label()
        return ("custom_mse", float(np.mean((preds - label) ** 2)), False)

    ds = lgb.Dataset(X, y)
    bst = lgb.train({**SMALL}, ds, 15, fobj=fobj, feval=feval,
                    valid_sets=[ds.create_valid(X, y)])
    p = bst.predict(X, raw_score=True)
    assert np.mean((p - y) ** 2) < 0.5 * np.var(y)


def test_missing_values(binary_data):
    X, y = binary_data
    Xn = X.copy()
    Xn[::5, 0] = np.nan
    bst = lgb.train({**SMALL, "objective": "binary"}, lgb.Dataset(Xn, y), 10)
    p = bst.predict(Xn)
    assert np.isfinite(p).all()
    # NaN rows route deterministically: same rows, same preds
    np.testing.assert_allclose(bst.predict(Xn), p)


def test_categorical_feature():
    rng = np.random.RandomState(5)
    n = 600
    cat = rng.randint(0, 5, n).astype(np.float64)
    Xo = rng.randn(n, 2)
    X = np.column_stack([cat, Xo])
    y = (np.isin(cat, [1, 3]).astype(np.float64) + 0.1 * rng.randn(n) > 0.5
         ).astype(np.float64)
    bst = lgb.train({**SMALL, "objective": "binary"},
                    lgb.Dataset(X, y, categorical_feature=[0]), 15)
    assert _auc(y, bst.predict(X)) > 0.95


def test_cv(regression_data):
    X, y = regression_data
    res = lgb.cv({**SMALL, "objective": "regression", "metric": "l2"},
                 lgb.Dataset(X, y), 8, nfold=3, stratified=False)
    assert "valid l2-mean" in res
    assert len(res["valid l2-mean"]) == 8
    assert res["valid l2-mean"][-1] < res["valid l2-mean"][0]


def test_max_depth(binary_data):
    X, y = binary_data
    bst = lgb.train({**SMALL, "objective": "binary", "max_depth": 2,
                     "num_leaves": 31}, lgb.Dataset(X, y), 5)
    d = bst.dump_model()

    def depth(node, cur=0):
        if "leaf_value" in node and "split_feature" not in node:
            return cur
        return max(depth(node["left_child"], cur + 1),
                   depth(node["right_child"], cur + 1))

    for ti in d["tree_info"]:
        if "split_feature" in ti["tree_structure"]:
            assert depth(ti["tree_structure"]) <= 2


def test_reset_parameter(regression_data):
    X, y = regression_data
    lrs = [0.3] * 4 + [0.05] * 4
    bst = lgb.train({**SMALL, "objective": "regression"}, lgb.Dataset(X, y), 8,
                    callbacks=[lgb.reset_parameter(learning_rate=lrs)])
    assert bst.num_trees() == 8
