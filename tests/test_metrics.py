"""Metric correctness tests against independent references."""

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.dataset import Metadata
from lightgbm_tpu.metric import create_metrics


def _eval_one(name, label, score, weight=None, group=None, extra=None):
    cfg = Config({"metric": [name], **(extra or {})})
    ms = create_metrics(cfg)
    assert len(ms) == 1
    md = Metadata()
    md.set_label(label)
    md.set_weight(weight)
    md.set_group(group)
    ms[0].init(md, len(label))
    return ms[0].eval(score)


def test_l2_rmse_l1():
    y = np.array([1.0, 2.0, 3.0])
    s = np.array([1.5, 2.0, 2.0])
    assert _eval_one("l2", y, s)[0][1] == pytest.approx((0.25 + 0 + 1) / 3)
    assert _eval_one("rmse", y, s)[0][1] == pytest.approx(
        np.sqrt((0.25 + 0 + 1) / 3))
    assert _eval_one("l1", y, s)[0][1] == pytest.approx(0.5)


def test_binary_logloss():
    y = np.array([1.0, 0.0, 1.0])
    p = np.array([0.9, 0.1, 0.8])
    s = np.log(p / (1 - p))  # raw scores
    want = -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))
    assert _eval_one("binary_logloss", y, s)[0][1] == pytest.approx(want, rel=1e-5)


def test_auc_matches_sklearn():
    from sklearn.metrics import roc_auc_score
    rng = np.random.RandomState(0)
    y = (rng.rand(300) > 0.6).astype(np.float64)
    s = rng.randn(300) + y
    got = _eval_one("auc", y, s)[0][1]
    assert got == pytest.approx(roc_auc_score(y, s), rel=1e-9)
    # weighted
    w = rng.rand(300) + 0.1
    got_w = _eval_one("auc", y, s, weight=w)[0][1]
    # weights are stored float32 internally -> small tolerance
    assert got_w == pytest.approx(roc_auc_score(y, s, sample_weight=w), rel=1e-6)


def test_auc_ties():
    y = np.array([1.0, 0, 1, 0])
    s = np.array([0.5, 0.5, 0.5, 0.5])
    assert _eval_one("auc", y, s)[0][1] == pytest.approx(0.5)


def test_average_precision():
    from sklearn.metrics import average_precision_score
    rng = np.random.RandomState(1)
    y = (rng.rand(200) > 0.7).astype(np.float64)
    s = rng.randn(200) + 2 * y
    got = _eval_one("average_precision", y, s)[0][1]
    assert got == pytest.approx(average_precision_score(y, s), rel=1e-6)


def test_multi_logloss():
    from sklearn.metrics import log_loss
    rng = np.random.RandomState(2)
    y = rng.randint(0, 3, 200).astype(np.float64)
    raw = rng.randn(200, 3)
    p = np.exp(raw) / np.exp(raw).sum(axis=1, keepdims=True)
    got = _eval_one("multi_logloss", y, raw, extra={"num_class": 3,
                                                    "objective": "multiclass"})[0][1]
    assert got == pytest.approx(log_loss(y, p, labels=[0, 1, 2]), rel=1e-5)


def test_multi_error():
    y = np.array([0.0, 1, 2, 1])
    raw = np.array([[3.0, 1, 1], [1, 3, 1], [1, 3, 1], [1, 3, 1]])
    got = _eval_one("multi_error", y, raw, extra={"num_class": 3,
                                                  "objective": "multiclass"})[0][1]
    assert got == pytest.approx(0.25)


def test_ndcg():
    # one query, perfect ranking -> 1.0
    y = np.array([3.0, 2, 1, 0])
    s = np.array([4.0, 3, 2, 1])
    res = _eval_one("ndcg", y, s, group=np.array([4]),
                    extra={"objective": "lambdarank", "eval_at": "2"})
    assert res[0][0] == "ndcg@2"
    assert res[0][1] == pytest.approx(1.0)
    # reversed ranking < 1
    res2 = _eval_one("ndcg", y, -s, group=np.array([4]),
                     extra={"objective": "lambdarank", "eval_at": "2"})
    assert res2[0][1] < 0.6


def test_map():
    y = np.array([1.0, 0, 1, 0])
    s = np.array([4.0, 3, 2, 1])
    res = _eval_one("map", y, s, group=np.array([4]),
                    extra={"objective": "lambdarank", "eval_at": "4"})
    # AP = (1/1 + 2/3)/2
    assert res[0][1] == pytest.approx((1.0 + 2.0 / 3.0) / 2)


def test_default_metric_for_objective():
    cfg = Config({"objective": "binary"})
    ms = create_metrics(cfg)
    assert ms[0].name == "binary_logloss"
    cfg = Config({"objective": "lambdarank"})
    assert create_metrics(cfg)[0].name == "ndcg"


def test_metric_aliases():
    cfg = Config({"objective": "regression", "metric": ["mse", "mae"]})
    names = [m.name for m in create_metrics(cfg)]
    assert names == ["l2", "l1"]
