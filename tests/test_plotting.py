"""Plotting module tests (reference test pattern:
tests/python_package_test/test_plotting.py — construct each plot object and
assert structure, no pixel comparisons)."""

import matplotlib
matplotlib.use("Agg")

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def trained():
    rng = np.random.RandomState(7)
    X = rng.randn(300, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    evals = {}
    ds = lgb.Dataset(X, y, feature_name=[f"f{i}" for i in range(5)])
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1,
                     "metric": "binary_logloss"}, ds, 10,
                    valid_sets=[ds.create_valid(X, y)],
                    callbacks=[lgb.record_evaluation(evals)])
    return bst, evals


def test_plot_importance(trained):
    bst, _ = trained
    ax = lgb.plot_importance(bst)
    assert ax.get_title() == "Feature importance"
    assert len(ax.patches) >= 1
    ax2 = lgb.plot_importance(bst, importance_type="gain",
                              max_num_features=2, title="t2")
    assert len(ax2.patches) <= 2


def test_plot_metric(trained):
    _, evals = trained
    ax = lgb.plot_metric(evals)
    assert ax.get_ylabel() == "binary_logloss"
    assert len(ax.get_lines()) == 1


def test_plot_split_value_histogram(trained):
    bst, _ = trained
    ax = lgb.plot_split_value_histogram(bst, 0)
    assert len(ax.patches) >= 1
    with pytest.raises(ValueError):
        # a feature never split on
        lgb.plot_split_value_histogram(bst, 4)


def test_create_tree_digraph(trained):
    bst, _ = trained
    graph = lgb.create_tree_digraph(
        bst, tree_index=0,
        show_info=["split_gain", "internal_count", "leaf_count"])
    src = graph.source
    assert "split0" in src and "leaf" in src
    with pytest.raises(IndexError):
        lgb.create_tree_digraph(bst, tree_index=99)


def test_no_unimplemented_params_remain():
    """Round-4 milestone: every accepted parameter is implemented (the
    warn-loudly list emptied as features landed)."""
    from lightgbm_tpu.config import _UNIMPLEMENTED_PARAMS
    assert _UNIMPLEMENTED_PARAMS == ()
