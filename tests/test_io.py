"""Text-loader tests (reference src/io/parser.cpp CreateParser detection +
dataset_loader.cpp two-round loading)."""

import numpy as np

from lightgbm_tpu.io_utils import _detect_format, load_data_file


def _write_csv(path, M, lab, header):
    with open(path, "w") as fh:
        fh.write(header + "\n")
        for i in range(len(M)):
            fh.write(",".join(
                [str(float(lab[i]))] +
                ["" if np.isnan(v) else str(float(v)) for v in M[i]]) + "\n")


def test_detect_format_colon_header_not_libsvm():
    assert _detect_format("label,a,b:1,c") == "csv"
    assert _detect_format("1 2:0.5 7:1.25") == "libsvm"
    assert _detect_format("0.5\t1.25\t3") == "tsv"


def test_dense_loader_nan_and_two_round(tmp_path):
    rng = np.random.RandomState(0)
    M = rng.randn(1000, 5)
    M[rng.rand(*M.shape) < 0.02] = np.nan
    lab = (rng.rand(1000) > 0.5).astype(float)
    p = str(tmp_path / "t.csv")
    _write_csv(p, M, lab, "label,a,b:1,c,d,e")
    f1, n1, l1 = load_data_file(p, {"header": "true"})
    f2, n2, l2 = load_data_file(p, {"header": "true", "two_round": "true"})
    np.testing.assert_array_equal(np.isnan(f1), np.isnan(M))
    np.testing.assert_allclose(np.nan_to_num(f1), np.nan_to_num(M))
    np.testing.assert_allclose(np.nan_to_num(f2), np.nan_to_num(f1))
    np.testing.assert_allclose(l1, lab)
    np.testing.assert_allclose(l2, lab)
    assert n1 == ["a", "b:1", "c", "d", "e"] == n2


def test_libsvm_loader(tmp_path):
    p = str(tmp_path / "t.svm")
    with open(p, "w") as fh:
        fh.write("1 0:0.5 3:2.0\n0 1:1.5\n1 2:-1.0 3:4.0\n")
    X, names, y = load_data_file(p, {})
    np.testing.assert_allclose(y, [1, 0, 1])
    np.testing.assert_allclose(X, [[0.5, 0, 0, 2.0],
                                   [0, 1.5, 0, 0],
                                   [0, 0, -1.0, 4.0]])


def test_misaligned_valid_set_raises():
    """A valid set constructed without reference to the train set has its
    own bin mappers — add_valid must refuse it (reference dataset.h:304
    alignment check), not silently evaluate on wrong leaf assignments."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X, y = rng.randn(300, 4), rng.randn(300)
    P = {"objective": "regression", "num_leaves": 7, "verbosity": -1}
    from lightgbm_tpu.config import Config
    vs = lgb.Dataset(X[:100], y[:100])
    vs.construct(Config(P))            # standalone mappers
    try:
        lgb.train(P, lgb.Dataset(X, y), 2, valid_sets=[vs])
    except ValueError as e:
        assert "reference" in str(e)
    else:
        raise AssertionError("misaligned valid set was accepted")


def test_unreferenced_valid_set_auto_aligns():
    """An unconstructed valid set without an explicit reference is aligned
    to the train set automatically (reference engine.py does the same)."""
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(1)
    X = rng.randn(400, 4)
    y = X[:, 0] + 0.1 * rng.randn(400)
    P = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
         "metric": "l2"}
    ev = {}
    bst = lgb.train(P, lgb.Dataset(X, y), 5,
                    valid_sets=[lgb.Dataset(X[:150], y[:150])],
                    valid_names=["v"],
                    callbacks=[lgb.record_evaluation(ev)])
    ref = float(np.mean((bst.predict(X[:150]) - y[:150]) ** 2))
    assert abs(ev["v"]["l2"][-1] - ref) < 1e-4 * max(1.0, ref)
