"""Monotone constraint tests (reference pattern:
tests/python_package_test/test_engine.py:1214-1327 — train with ±1
constraints and assert predictions are monotone in the constrained feature
while other features vary)."""

import numpy as np
import pytest

from conftest import FP_SKIP

import lightgbm_tpu as lgb


def _gen(n=1200, seed=0):
    rng = np.random.RandomState(seed)
    x0 = rng.rand(n)          # constrained +1
    x1 = rng.rand(n)          # constrained -1
    x2 = rng.rand(n)          # free
    # true relationship is NOT monotone in x0/x1 so the constraint binds
    y = (5 * x0 + np.sin(10 * np.pi * x0)
         - 5 * x1 - np.cos(10 * np.pi * x1)
         + 10 * x2 + rng.randn(n) * 0.1)
    return np.stack([x0, x1, x2], 1), y


def _is_monotone(bst, feature, sign, n_checks=20):
    rng = np.random.RandomState(99)
    grid = np.linspace(0.0, 1.0, 101)
    for _ in range(n_checks):
        row = rng.rand(3)
        batch = np.tile(row, (101, 1))
        batch[:, feature] = grid
        pred = bst.predict(batch)
        diffs = np.diff(pred)
        if sign > 0 and (diffs < -1e-9).any():
            return False
        if sign < 0 and (diffs > 1e-9).any():
            return False
    return True


PARAMS = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
          "metric": "l2", "monotone_constraints": [1, -1, 0]}


def test_unconstrained_is_not_monotone():
    X, y = _gen()
    bst = lgb.train({k: v for k, v in PARAMS.items()
                     if k != "monotone_constraints"}, lgb.Dataset(X, y), 60)
    assert not _is_monotone(bst, 0, +1)


@pytest.mark.parametrize("extra", [{}, {"monotone_penalty": 2.0}])
def test_monotone_serial(extra):
    X, y = _gen()
    bst = lgb.train({**PARAMS, **extra}, lgb.Dataset(X, y), 60)
    assert _is_monotone(bst, 0, +1)
    assert _is_monotone(bst, 1, -1)
    # the model still learns something useful
    resid = np.mean((bst.predict(X) - y) ** 2)
    assert resid < np.var(y) * 0.5


def test_monotone_config_string_alias():
    X, y = _gen()
    bst = lgb.train({**PARAMS, "monotone_constraints": "1,-1,0"},
                    lgb.Dataset(X, y), 40)
    assert _is_monotone(bst, 0, +1)


def test_monotone_data_parallel():
    X, y = _gen()
    bst = lgb.train({**PARAMS, "tree_learner": "data", "num_devices": 4},
                    lgb.Dataset(X, y), 40)
    assert _is_monotone(bst, 0, +1)
    assert _is_monotone(bst, 1, -1)


@FP_SKIP
def test_monotone_feature_parallel():
    X, y = _gen()
    bst = lgb.train({**PARAMS, "tree_learner": "feature", "num_devices": 4},
                    lgb.Dataset(X, y), 30)
    assert _is_monotone(bst, 0, +1)


def test_monotone_penalty_reduces_monotone_splits():
    X, y = _gen()
    b0 = lgb.train(PARAMS, lgb.Dataset(X, y), 40)
    # small penalties only push monotone splits deeper; a penalty larger
    # than the max depth suppresses them outright (factor ~eps at d < p-1)
    b9 = lgb.train({**PARAMS, "monotone_penalty": 10.0}, lgb.Dataset(X, y), 40)

    def mono_split_count(bst):
        total = 0
        for tree in bst._gbdt.models:
            sf = tree.split_feature[:tree.num_leaves - 1]
            total += int(np.sum((sf == 0) | (sf == 1)))
        return total
    # high penalty discourages splits on the constrained features
    assert mono_split_count(b9) < mono_split_count(b0)


def test_monotone_intermediate_wave():
    """monotone_constraints_method=intermediate on the wave grower:
    constraints hold under the region-box propagation, and the looser
    sibling-output bounds fit at least as well as basic."""
    X, y = _gen()
    base = {**PARAMS, "tree_grow_mode": "wave"}
    bst_b = lgb.train({**base, "monotone_constraints_method": "basic"},
                      lgb.Dataset(X, y), 60)
    bst_i = lgb.train({**base, "monotone_constraints_method": "intermediate"},
                      lgb.Dataset(X, y), 60)
    assert _is_monotone(bst_i, 0, +1)
    assert _is_monotone(bst_i, 1, -1)
    mse_b = np.mean((bst_b.predict(X) - y) ** 2)
    mse_i = np.mean((bst_i.predict(X) - y) ** 2)
    # intermediate is less constraining: fit must not be (meaningfully)
    # worse than basic
    assert mse_i <= mse_b * 1.02 + 1e-6
    # 'advanced' downgrades to intermediate with a warning, still monotone
    bst_a = lgb.train({**base, "monotone_constraints_method": "advanced"},
                      lgb.Dataset(X, y), 30)
    assert _is_monotone(bst_a, 0, +1)
