"""Wave grower tests (learner/wave.py).

The wave grower must (a) reproduce the exact sequential leaf-wise order at
wave_size=1, (b) stay quality-par at wave_size=16, and (c) support the
same feature set as the partitioned grower minus the gated ones (forced
splits / interaction constraints / bynode), incl. EFB, categoricals,
monotone constraints and GOSS."""

import numpy as np
import scipy.sparse as sp

import lightgbm_tpu as lgb


def _binary(n=4000, f=8, seed=0, nan_frac=0.05):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    if nan_frac:
        X[rng.rand(n, f) < nan_frac] = np.nan
    w = rng.randn(f)
    y = ((np.nan_to_num(X) @ w + 0.5 * rng.randn(n)) > 0).astype(np.float64)
    return X, y


def _logloss(y, p):
    p = np.clip(p, 1e-9, 1 - 1e-9)
    return -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))


def _params(mode, wave=16, **kw):
    p = {"objective": "binary", "num_leaves": 31, "max_bin": 63,
         "learning_rate": 0.2, "verbosity": -1, "min_data_in_leaf": 20,
         "tree_grow_mode": mode, "tpu_wave_size": wave}
    p.update(kw)
    return p


def test_wave1_matches_sequential_exactly():
    X, y = _binary()
    pred_p = lgb.train(_params("partition"), lgb.Dataset(X, y),
                       num_boost_round=6).predict(X)
    pred_w = lgb.train(_params("wave", wave=1), lgb.Dataset(X, y),
                       num_boost_round=6).predict(X)
    np.testing.assert_allclose(pred_w, pred_p, atol=2e-4)


def test_wave16_quality_parity():
    X, y = _binary()
    ll_p = _logloss(y, lgb.train(_params("partition"), lgb.Dataset(X, y),
                                 num_boost_round=10).predict(X))
    ll_w = _logloss(y, lgb.train(_params("wave"), lgb.Dataset(X, y),
                                 num_boost_round=10).predict(X))
    assert ll_w < ll_p * 1.05 + 1e-3


def test_wave_regression_and_bagging():
    rng = np.random.RandomState(1)
    X = rng.randn(3000, 6).astype(np.float32)
    y = X[:, 0] * 2 - X[:, 1] + 0.3 * rng.randn(3000)
    p = _params("wave", objective="regression", metric="l2",
                bagging_fraction=0.7, bagging_freq=1)
    bst = lgb.train(p, lgb.Dataset(X, y), num_boost_round=15)
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < 0.5 * float(np.var(y))


def test_wave_goss():
    X, y = _binary(nan_frac=0)
    p = _params("wave", boosting="goss")
    bst = lgb.train(p, lgb.Dataset(X, y), num_boost_round=10)
    assert _logloss(y, bst.predict(X)) < 0.6


def test_wave_categorical():
    rng = np.random.RandomState(3)
    n = 3000
    c = rng.randint(0, 12, n)
    x1 = rng.randn(n)
    y = (np.isin(c, [1, 3, 7]).astype(float) * 2 + x1 +
         0.2 * rng.randn(n) > 1).astype(np.float64)
    X = np.stack([c.astype(np.float32), x1.astype(np.float32)], 1)
    p = _params("wave", max_cat_to_onehot=4)
    bst = lgb.train(p, lgb.Dataset(X, y, categorical_feature=[0]),
                    num_boost_round=10)
    assert _logloss(y, bst.predict(X)) < 0.35


def test_wave_monotone():
    rng = np.random.RandomState(4)
    n = 2000
    x0 = rng.rand(n)
    x1 = rng.rand(n)
    y = 5 * x0 + np.sin(10 * np.pi * x0) + 3 * x1 + 0.1 * rng.randn(n)
    X = np.stack([x0, x1], 1).astype(np.float32)
    p = _params("wave", objective="regression",
                monotone_constraints=[1, 0], learning_rate=0.1)
    bst = lgb.train(p, lgb.Dataset(X, y), num_boost_round=20)
    grid = np.linspace(0, 1, 101)
    for _ in range(10):
        row = rng.rand(2)
        batch = np.tile(row, (101, 1))
        batch[:, 0] = grid
        assert (np.diff(bst.predict(batch)) >= -1e-9).all()


def test_wave_efb_sparse():
    rng = np.random.RandomState(5)
    n, f = 2500, 40
    X = np.zeros((n, f))
    X[:, 0] = rng.randn(n)
    for j in range(1, f):
        rows = rng.choice(n, size=int(n * 0.02), replace=False)
        X[rows, j] = rng.rand(len(rows)) + 0.5
    y = X[:, 0] + 2.0 * (X[:, 7] > 0) - (X[:, 11] > 0) + 0.1 * rng.randn(n)
    p = _params("wave", objective="regression", metric="l2",
                min_data_in_leaf=5)
    bst = lgb.train(p, lgb.Dataset(sp.csr_matrix(X), y), num_boost_round=15)
    dense_p = dict(p, enable_bundle=False)
    bst_d = lgb.train(dense_p, lgb.Dataset(X, y), num_boost_round=15)
    mse_b = float(np.mean((bst.predict(X) - y) ** 2))
    mse_d = float(np.mean((bst_d.predict(X) - y) ** 2))
    assert mse_b < max(1.3 * mse_d, mse_d + 0.02)


def test_wave_falls_back_when_gated():
    X, y = _binary(nan_frac=0)
    p = _params("wave", feature_fraction_bynode=0.5)
    bst = lgb.train(p, lgb.Dataset(X, y), num_boost_round=3)
    assert bst.current_iteration == 3  # fell back, still trains


def test_wave_multiclass():
    rng = np.random.RandomState(6)
    n = 3000
    X = rng.randn(n, 6).astype(np.float32)
    y = (X[:, 0] > 0.5).astype(int) + (X[:, 1] > 0).astype(int)
    p = _params("wave", objective="multiclass", num_class=3,
                metric="multi_logloss")
    bst = lgb.train(p, lgb.Dataset(X, y.astype(float)), num_boost_round=8)
    acc = float(np.mean(np.argmax(bst.predict(X), axis=1) == y))
    assert acc > 0.75


def test_wave_sample_weights_match_partition():
    """Row weights ride the gradient/hessian channels (bag mask may be
    non-0/1); wave_size=1 must still reproduce the sequential order."""
    rng = np.random.RandomState(9)
    X = rng.randn(3000, 6).astype(np.float32)
    y = ((X[:, 0] - 0.5 * X[:, 1]) > 0).astype(np.float64)
    w = rng.uniform(0.2, 3.0, 3000)
    pred = {}
    for mode, ws in (("partition", 16), ("wave", 1)):
        p = _params(mode, wave=ws)
        bst = lgb.train(p, lgb.Dataset(X, y, weight=w), num_boost_round=6)
        pred[mode] = bst.predict(X)
    np.testing.assert_allclose(pred["wave"], pred["partition"], atol=2e-4)


def test_wave_forced_splits(tmp_path):
    """ForceSplits on the wave grower: pre-committed waves apply the BFS
    prefix (no more fallback to the partitioned grower), then gain-driven
    growth resumes; numbering matches the partitioned grower's."""
    import json
    X, y = _binary(nan_frac=0.0)
    fs = {"feature": 5, "threshold": 0.0,
          "left": {"feature": 4, "threshold": 0.5},
          "right": {"feature": 3, "threshold": -0.2}}
    path = str(tmp_path / "forced.json")
    json.dump(fs, open(path, "w"))
    pw = _params("wave", forcedsplits_filename=path)
    bst = lgb.train(pw, lgb.Dataset(X, y), 5)
    for tree in bst._gbdt.models:
        assert tree.split_feature[0] == 5
        assert {int(tree.split_feature[1]), int(tree.split_feature[2])} == \
            {4, 3}
    # quality parity with the partitioned grower under the same forcing
    pp = _params("partition", forcedsplits_filename=path)
    ll_w = _logloss(y, bst.predict(X))
    ll_p = _logloss(y, lgb.train(pp, lgb.Dataset(X, y), 5).predict(X))
    assert ll_w < ll_p * 1.05 + 1e-3
