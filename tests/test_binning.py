"""BinMapper tests (reference src/io/bin.cpp FindBin semantics)."""

import numpy as np

from lightgbm_tpu.binning import MissingType, bin_matrix, find_bin


def test_simple_numeric():
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0] * 20)
    m = find_bin(vals, max_bin=255, min_data_in_bin=1)
    b = m.value_to_bin(np.array([1.0, 2.0, 3.0, 4.0, 5.0]))
    # distinct values -> distinct bins, monotone
    assert len(set(b.tolist())) == 5
    assert all(b[i] < b[i + 1] for i in range(4))


def test_monotone_mapping():
    rng = np.random.RandomState(0)
    vals = rng.randn(5000)
    m = find_bin(vals, max_bin=63, min_data_in_bin=3)
    xs = np.sort(rng.randn(100))
    bs = m.value_to_bin(xs)
    assert (np.diff(bs) >= 0).all()
    assert bs.max() < m.num_bin


def test_max_bin_respected():
    rng = np.random.RandomState(1)
    vals = rng.randn(10000)
    for mb in (15, 63, 255):
        m = find_bin(vals, max_bin=mb, min_data_in_bin=1)
        assert 1 < m.num_bin <= mb


def test_zero_gets_own_bin():
    vals = np.concatenate([np.zeros(50), np.linspace(-3, 3, 100)])
    m = find_bin(vals, max_bin=32, min_data_in_bin=1)
    zb = m.value_to_bin(np.array([0.0]))[0]
    nonzero = m.value_to_bin(np.array([-3.0, -0.1, 0.1, 3.0]))
    assert zb not in nonzero.tolist()
    assert m.default_bin == zb


def test_nan_bin():
    vals = np.array([1.0, 2.0, np.nan, 3.0, np.nan] * 10)
    m = find_bin(vals, max_bin=16, min_data_in_bin=1, use_missing=True)
    assert m.missing_type == MissingType.NAN
    b = m.value_to_bin(np.array([np.nan, 1.0]))
    assert b[0] == m.num_bin - 1  # trailing NaN bin
    assert b[1] != b[0]


def test_no_use_missing():
    vals = np.array([1.0, 2.0, np.nan, 3.0] * 10)
    m = find_bin(vals, max_bin=16, min_data_in_bin=1, use_missing=False)
    assert m.missing_type == MissingType.NONE
    # NaN folds into the zero bin
    assert m.value_to_bin(np.array([np.nan]))[0] == m.value_to_bin(
        np.array([0.0]))[0]


def test_categorical():
    vals = np.array([0, 1, 1, 2, 2, 2, 5, 5, 5, 5] * 10, dtype=np.float64)
    m = find_bin(vals, max_bin=32, min_data_in_bin=1, is_categorical=True)
    assert m.is_categorical
    b = m.value_to_bin(np.array([5.0, 2.0, 1.0, 0.0]))
    # bins ordered by descending frequency: 5 -> 0, 2 -> 1, 1 -> 2, 0 -> 3
    assert b.tolist() == [0, 1, 2, 3]
    # unseen category -> bin 0 (most frequent)
    assert m.value_to_bin(np.array([99.0]))[0] == 0
    # NaN -> most frequent bin
    assert m.value_to_bin(np.array([np.nan]))[0] == 0


def test_trivial_feature():
    m = find_bin(np.ones(100), max_bin=32)
    assert m.is_trivial


def test_bin_matrix_dtype():
    rng = np.random.RandomState(2)
    X = rng.randn(100, 3)
    mappers = [find_bin(X[:, j], max_bin=255, min_data_in_bin=1)
               for j in range(3)]
    binned = bin_matrix(X, mappers)
    assert binned.dtype == np.uint8
    assert binned.shape == (100, 3)


def test_bin_to_value_roundtrip():
    rng = np.random.RandomState(3)
    vals = rng.randn(1000)
    m = find_bin(vals, max_bin=63, min_data_in_bin=1)
    # threshold semantics: value <= bin_to_value(b) <=> bin(value) <= b
    for b in range(0, m.num_bin - 1, 7):
        thr = m.bin_to_value(b)
        xs = rng.randn(200)
        lhs = xs <= thr
        rhs = m.value_to_bin(xs) <= b
        assert (lhs == rhs).all()


def test_forced_bins(tmp_path):
    """forcedbins_filename (reference dataset_loader.cpp GetForcedBins):
    listed boundaries must appear among the feature's bin upper bounds."""
    import json
    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    rng = np.random.RandomState(0)
    X = rng.rand(2000, 3)
    y = (X[:, 0] > 0.33).astype(float)
    fb = str(tmp_path / "forced.json")
    with open(fb, "w") as fh:
        json.dump([{"feature": 0, "bin_upper_bound": [0.3, 0.35, 0.4]}], fh)
    P = {"objective": "binary", "verbosity": -1, "max_bin": 16,
         "forcedbins_filename": fb}
    ds = lgb.Dataset(X, y, params=P)
    ds.construct(Config(P))
    ub = ds.bin_mappers[0].bin_upper_bound
    for b in (0.3, 0.35, 0.4):
        assert np.any(np.isclose(ub, b)), (b, ub)
    # still trains
    bst = lgb.train(P, lgb.Dataset(X, y), 3)
    assert np.isfinite(bst.predict(X[:10])).all()


def test_forced_bins_capped_and_zero_bin_preserved():
    """Forced bounds are capped at max_bin (reference caps too) and the
    dedicated zero/missing bin survives the merge."""
    from lightgbm_tpu.binning import find_bin
    rng = np.random.RandomState(0)
    v = rng.rand(5000) * 10
    m = find_bin(v, max_bin=8, forced_bounds=list(np.linspace(0.1, 9.9, 40)))
    assert m.num_bin <= 9
    v2 = np.concatenate([np.zeros(1000), rng.rand(4000)])
    m2 = find_bin(v2, max_bin=8, zero_as_missing=True,
                  forced_bounds=list(np.linspace(0.1, 0.9, 14)))
    assert m2.value_to_bin(np.array([0.0]))[0] != \
        m2.value_to_bin(np.array([0.2]))[0]
