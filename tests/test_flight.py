"""Training flight recorder (telemetry/flight.py).

The PR-3 telemetry invariant extended: recorder-on training is
bit-identical to recorder-off (model text + predictions), the event
ring is bounded, anomaly detection flags NaN/spiking losses, and the
resilience path leaves a JSONL post-mortem whose last event matches the
checkpoint iteration on a SIGTERM (preemption) or an injected crash.
"""

import json
import os
import signal

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.telemetry.flight import FlightRecorder


def _data(n=600, f=6, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] + 0.4 * rng.randn(n) > 0).astype(float)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "metric": "binary_logloss", "seed": 3}


def test_flight_recorder_bit_identical_on_off():
    X, y = _data()
    on = lgb.train(PARAMS, lgb.Dataset(X, y), 10)
    off = lgb.train({**PARAMS, "flight_recorder": False},
                    lgb.Dataset(X, y), 10)
    assert on.model_to_string() == off.model_to_string()
    assert np.array_equal(on.predict(X), off.predict(X))
    assert len(on._gbdt.flight) == 10
    assert len(off._gbdt.flight) == 0 and not off._gbdt.flight.enabled


def test_flight_ring_is_bounded_and_events_structured():
    X, y = _data()
    bst = lgb.train({**PARAMS, "flight_events": 6}, lgb.Dataset(X, y),
                    15, valid_sets=[lgb.Dataset(X, y)])
    fr = bst._gbdt.flight
    assert len(fr) == 6                      # ring kept the tail only
    evs = fr.events()
    assert [e["iteration"] for e in evs] == list(range(10, 16))
    last = evs[-1]
    assert last["num_leaves"] >= 1 and isinstance(last["num_leaves"], int)
    assert last["best_gain"] is None or \
        isinstance(last["best_gain"], float)
    assert "valid_0 binary_logloss" in last["evals"]
    assert last["loss"] == pytest.approx(
        last["evals"]["valid_0 binary_logloss"])
    assert last["anomaly"] is None


def test_flight_anomaly_detection_nan_and_spike():
    from lightgbm_tpu.telemetry.metrics import default_registry
    fr = FlightRecorder(capacity=64, min_history=2)
    c = default_registry().get("flight_anomalies_total")
    base_nan = c.value(kind="nan_loss")
    base_spike = c.value(kind="loss_spike")
    for i in range(1, 6):
        fr.note_iter(i)
        fr.note_eval(i, [("train", "l2", 0.5, False)])
    fr.note_iter(6)
    fr.note_eval(6, [("train", "l2", 50.0, False)])     # 100x the EWMA
    fr.note_iter(7)
    fr.note_eval(7, [("train", "l2", float("nan"), False)])
    kinds = [a["kind"] for a in fr.anomalies]
    assert kinds == ["loss_spike", "nan_loss"]
    assert c.value(kind="nan_loss") == base_nan + 1
    assert c.value(kind="loss_spike") == base_spike + 1
    evs = fr.events()
    assert evs[-2]["anomaly"] == "loss_spike"
    assert evs[-1]["anomaly"] == "nan_loss"


def _read_tape(path):
    with open(path) as fh:
        lines = [json.loads(ln) for ln in fh]
    assert lines[0]["schema"] == "flight-record-v1"
    return lines[0], lines[1:]


@pytest.mark.chaos
def test_sigterm_flight_dump_matches_checkpoint_iteration(tmp_path):
    """The acceptance invariant: a chaos-style interrupted run (SIGTERM
    mid-train) leaves a flight JSONL whose last event iteration equals
    the final checkpoint's iteration — same drained boundary."""
    from lightgbm_tpu.resilience.checkpoint import (TrainingPreempted,
                                                    load_checkpoint,
                                                    resolve_checkpoint)
    X, y = _data()
    ck = str(tmp_path / "ck")

    def killer(env):
        if env.iteration == 5:
            os.kill(os.getpid(), signal.SIGTERM)
    killer.before_iteration = True

    with pytest.raises(TrainingPreempted):
        lgb.train({**PARAMS, "checkpoint_dir": ck}, lgb.Dataset(X, y), 40,
                  valid_sets=[lgb.Dataset(X, y)], callbacks=[killer])
    header, events = _read_tape(os.path.join(ck, "flight.jsonl"))
    assert header["reason"] == "preempted"
    ckpt = load_checkpoint(resolve_checkpoint(ck))
    assert events[-1]["iteration"] == ckpt.iteration
    # the tape carries the observability payload, not bare iteration ids
    assert "evals" in events[-1] and "collective_bytes" in events[-1]


@pytest.mark.chaos
def test_injected_crash_dumps_flight_tape(tmp_path):
    from lightgbm_tpu.resilience.faults import InjectedFault, faults
    X, y = _data()
    ck = str(tmp_path / "ck")
    faults.configure("crash_at_iter=4")
    try:
        with pytest.raises(InjectedFault):
            lgb.train({**PARAMS, "checkpoint_dir": ck},
                      lgb.Dataset(X, y), 20)
    finally:
        faults.clear()
    header, events = _read_tape(os.path.join(ck, "flight.jsonl"))
    assert header["reason"] == "crash"
    assert events[-1]["iteration"] == 4   # iterations completed pre-crash


def test_explicit_flight_dir_dumps_on_success(tmp_path):
    X, y = _data()
    fd = str(tmp_path / "tape")
    os.makedirs(fd)
    lgb.train({**PARAMS, "flight_dir": fd}, lgb.Dataset(X, y), 6)
    header, events = _read_tape(os.path.join(fd, "flight.jsonl"))
    assert header["reason"] == "completed"
    assert events[-1]["iteration"] == 6
