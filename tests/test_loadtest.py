"""Synthetic load harness + per-request tracing satellites.

Covers: the Prometheus text parser the verdict path rides, the
micro-batcher saturation gauges (queue depth / in-flight), the
queue-wait vs device-compute split recording, the end-to-end load test
(real HTTP server, verdict computed solely from /metrics + /slo
scrapes), and the tracing overhead guard (< 5% of p50 at the smallest
bucket).  The full 10^5 rows/s acceptance rung is slow-marked (CI runs
it as the blocking loadtest step; tier-1 runs the reduced-rate e2e).
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve.loadgen import metric_sum, parse_prometheus


# ---------------------------------------------------------------------------
# scrape parsing (the verdict path)
# ---------------------------------------------------------------------------

def test_parse_prometheus_text():
    text = "\n".join([
        "# HELP lgbm_tpu_serve_rows_total data rows",
        "# TYPE lgbm_tpu_serve_rows_total counter",
        'lgbm_tpu_serve_rows_total{model="m"} 1234',
        'lgbm_tpu_serve_rows_total{model="n"} 6',
        'lgbm_tpu_serve_request_latency_ms_p99{bucket="4096",model="m"} 7.5',
        "lgbm_tpu_up 1",
        "garbage line without value",
    ])
    parsed = parse_prometheus(text)
    assert metric_sum(parsed, "lgbm_tpu_serve_rows_total") == 1240
    assert metric_sum(parsed, "lgbm_tpu_serve_rows_total", model="m") == 1234
    assert metric_sum(parsed, "lgbm_tpu_serve_request_latency_ms_p99",
                      model="m", bucket="4096") == 7.5
    assert metric_sum(parsed, "lgbm_tpu_up") == 1.0
    assert "garbage" not in parsed


def test_loadgen_survives_worker_restarts():
    """Satellite: connection errors count as failed requests in the
    client tally (connect_errors) instead of aborting the generator
    thread, one bounded reconnect retries the request on a fresh
    socket, and the connect/read timeout is configurable per spec."""
    import json as _json
    import socket
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from lightgbm_tpu.serve.loadgen import LoadGenerator, LoadSpec

    hits = [0]

    class Flaky(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            hits[0] += 1
            if hits[0] % 5 == 0:      # sever every 5th connection
                try:
                    self.connection.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                self.close_connection = True
                return
            body = _json.dumps({"predictions": [0.0]}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Flaky)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        spec = LoadSpec(duration_s=1.0, target_qps=60.0, workers=2,
                        features=3, bucket_mix={1: 1.0}, timeout_s=5.0)
        gen = LoadGenerator("127.0.0.1", srv.server_address[1], spec)
        res = gen.run()
        # the generator survived every severed connection: requests
        # kept flowing after the resets, each one reached a terminal
        # outcome (a code or a connect_error after the one retry)
        assert res.requests_sent > 20
        assert res.by_code.get(200, 0) > 10
        assert sum(res.by_code.values()) + res.connect_errors == \
            res.requests_sent
        assert res.summary()["connect_errors"] == res.connect_errors
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# batcher saturation gauges + timing split
# ---------------------------------------------------------------------------

def test_queue_and_inflight_gauges():
    from lightgbm_tpu.serve.batcher import MicroBatcher
    from lightgbm_tpu.telemetry.metrics import default_registry
    release = threading.Event()

    def slow_fn(X, raw):
        release.wait(10.0)
        return np.zeros(X.shape[0], np.float32)

    mb = MicroBatcher(slow_fn, max_batch_rows=4, name="t_gauges")
    qg = default_registry().get("serve_queue_rows")
    ig = default_registry().get("serve_inflight_requests")
    try:
        futs = [mb.submit(np.zeros((2, 3), np.float32))]
        time.sleep(0.1)          # worker picks it up, blocks in slow_fn
        futs.append(mb.submit(np.zeros((3, 3), np.float32)))
        time.sleep(0.05)
        # one request is being served, one is queued: the gauges show
        # saturation building while nothing has been shed yet
        assert qg.value(model="t_gauges") == 3.0
        assert ig.value(model="t_gauges") == 2.0
        assert mb.backlog_rows == 3 and mb.inflight_requests() == 2
        release.set()
        for f in futs:
            f.result(timeout=10.0)
        time.sleep(0.1)
        assert qg.value(model="t_gauges") == 0.0
        assert ig.value(model="t_gauges") == 0.0
    finally:
        release.set()
        mb.close()


def test_request_timing_split_recorded():
    from lightgbm_tpu.serve.batcher import MicroBatcher
    from lightgbm_tpu.serve.stats import ModelStats

    def fn(X, raw):
        time.sleep(0.01)
        return np.zeros(X.shape[0], np.float32)

    stats = ModelStats(model="t_split")     # private registry
    mb = MicroBatcher(fn, stats=stats, name="t_split")
    try:
        for _ in range(5):
            mb.predict(np.zeros((3, 4), np.float32))
    finally:
        mb.close()
    t = stats.bucket_timing(8)              # 3 rows -> bucket 8
    assert len(t["request_latency_ms"]) == 5
    assert len(t["queue_wait_ms"]) == 5 and len(t["device_ms"]) == 5
    for total, q, d in zip(sorted(t["request_latency_ms"]),
                           sorted(t["queue_wait_ms"]),
                           sorted(t["device_ms"])):
        assert d >= 10.0                    # the sleep is device time
        assert total + 1e-6 >= d            # split components bound total
    snap = stats.snapshot()
    assert snap["request_latency_ms"]["window"] == 5
    assert snap["device_ms"]["p50"] >= 10.0


def test_request_ids_propagate_to_predictor_and_exemplars():
    from lightgbm_tpu.serve.batcher import MicroBatcher
    from lightgbm_tpu.serve.stats import ModelStats, request_exemplars
    seen = []

    def fn(X, raw, request_ids=()):
        seen.extend(request_ids)
        return np.zeros(X.shape[0], np.float32)

    stats = ModelStats(model="t_rids")
    # the ring keeps the process-wide slowest N: drop earlier tests'
    # entries so these near-instant requests qualify
    request_exemplars().clear()
    mb = MicroBatcher(fn, stats=stats, name="t_rids")
    try:
        mb.predict(np.zeros((2, 3), np.float32), request_id="rid-a")
        mb.predict(np.zeros((2, 3), np.float32), request_id="rid-b")
    finally:
        mb.close()
    assert seen == ["rid-a", "rid-b"]
    ids = {e["request_id"] for e in request_exemplars().snapshot()}
    assert {"rid-a", "rid-b"} <= ids


# ---------------------------------------------------------------------------
# end-to-end harness (reduced rate in tier-1; full rate slow-marked)
# ---------------------------------------------------------------------------

def _run_loadtest(**kw):
    bench_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks")
    sys.path.insert(0, bench_dir)
    try:
        import loadtest
        return loadtest.run_loadtest(**kw)
    finally:
        sys.path.remove(bench_dir)


def test_loadtest_e2e_verdict_from_scrapes():
    report = _run_loadtest(ladder=("5", "closed"), duration_s=1.5,
                           workers=2, trees=5, leaves=7,
                           bucket_mix={512: 0.5, 64: 0.5},
                           target_rows_per_s=1000.0,
                           p99_threshold_ms=5000.0,
                           scrape_interval_s=0.3)
    assert report["schema"] == "loadtest-slo-report-v1"
    assert report["verdict"] == "pass", report
    assert report["verdict_source"] == "/metrics + /slo scrapes only"
    assert len(report["rungs"]) == 2
    open_rung, closed_rung = report["rungs"]
    assert open_rung["label"] == "qps5" and closed_rung["label"] == "closed"
    for rung in report["rungs"]:
        # the verdict inputs all came from the server's own telemetry
        assert rung["rows_per_sec"] > 0 and rung["qps"] > 0
        assert rung["availability"] == 1.0
        assert rung["slo"]["schema"] == "slo-report-v1"
        assert rung["per_bucket"], rung
        for b, lat in rung["per_bucket"].items():
            assert lat["p99_ms"] > 0
            assert lat["device_p50_ms"] > 0
    # bench-matrix-v1 record rows (the nightly regression gate's diet)
    import loadtest as lt
    rec = lt.to_bench_matrix(report)
    names = [r["name"] for r in rec["rows"]]
    assert rec["schema"] == "bench-matrix-v1"
    assert "loadtest_closed" in names and "loadtest_slo" in names
    assert "loadtest_closed_qps" in names   # qps judged on its own row
    assert any(n.startswith("loadtest_closed_p99_b") for n in names)


@pytest.mark.slow
def test_explain_loadtest_verdict_from_scrapes():
    """The CI --explain smoke: closed-loop /explain traffic with
    interleaved /predict requests; pass requires a 5xx-free explain
    response counter, the explain-latency SLO met on /slo, zero
    dense->walk fallback batches, and a clean predict lane — all read
    from the server's own telemetry."""
    bench_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks")
    sys.path.insert(0, bench_dir)
    try:
        import loadtest
        report = loadtest.run_explain_loadtest(
            duration_s=1.5, threads_n=2, rows_per_req=8, trees=5,
            leaves=7, p99_threshold_ms=5000.0, scrape_interval_s=0.3)
        assert report["schema"] == "explain-loadtest-report-v1"
        assert report["verdict"] == "pass", report
        assert report["verdict_source"] == "/metrics + /slo scrapes only"
        assert report["availability"] == 1.0
        assert report["dense_ok"] and report["fallback_batches"] == 0
        assert report["volume_ok"] and report["explain_qps"] > 0
        # additivity held across the HTTP boundary (context, not verdict)
        assert report["additive_ok"]
        # the explain SLO itself was evaluated, not just the global ok
        assert report["explain_slo"].get("name") == \
            "serve/explain_latency_p99"
        assert report["explain_slo"].get("ok") is True
        assert report["per_bucket"], report
        rec = loadtest.explain_to_bench_matrix(report)
        names = [r["name"] for r in rec["rows"]]
        assert rec["schema"] == "bench-matrix-v1"
        assert "explain_loadtest" in names
        assert "explain_fallbacks" in names
        assert "explain_verdict" in names
        assert any(n.startswith("explain_loadtest_p99_b") for n in names)
    finally:
        sys.path.remove(bench_dir)


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_chaos_harness_verdict_from_scrapes():
    """The CI fleet-chaos smoke: serve_crash_after_n kills one worker
    under loadgen traffic, and the pass verdict (crashed + recovered +
    availability SLO met + every request terminal) comes exclusively
    from fleet /metrics + /slo scrapes."""
    bench_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks")
    sys.path.insert(0, bench_dir)
    try:
        import loadtest
        report = loadtest.run_fleet_chaos(
            workers=2, duration_s=4.0, qps=25.0, crash_after=15,
            recovery_window_s=20.0)
        assert report["verdict"] == "pass", report
        assert report["crashed"] and report["recovered"]
        assert report["slo_ok"] and report["all_requests_terminal"]
        assert report["fleet_restarts_total"] >= 1
        assert report["verdict_source"] == \
            "fleet /metrics + /slo scrapes only"
        rec = loadtest.fleet_chaos_to_bench_matrix(report)
        names = [r["name"] for r in rec["rows"]]
        assert "fleet_chaos" in names and "fleet_chaos_slo" in names
    finally:
        sys.path.remove(bench_dir)


@pytest.mark.slow
def test_loadtest_sustains_1e5_rows_per_sec():
    """ROADMAP item 3 acceptance: >= 10^5 synthetic rows/s through the
    real HTTP serving tier on this env, judged from /metrics scrapes
    (the CI loadtest step runs the same harness blocking)."""
    report = _run_loadtest(ladder=("closed",), duration_s=6.0, workers=3,
                           target_rows_per_s=1e5,
                           p99_threshold_ms=2000.0)
    assert report["verdict"] == "pass", report
    assert report["peak_rows_per_sec"] >= 1e5


# ---------------------------------------------------------------------------
# tracing overhead guard
# ---------------------------------------------------------------------------

def test_per_request_tracing_overhead_under_5pct_p50():
    """The per-request tracing add-on (three histogram observes + an
    exemplar offer) must cost < 5% of the p50 request latency at the
    SMALLEST bucket.  Both sides take the MIN over repeated rounds —
    the minimum of a wall-time measurement is robust to the scheduler
    jitter / GC pauses a shared 1-core CI runner injects, where a
    single-round mean is not."""
    from lightgbm_tpu.telemetry.metrics import percentile
    rng = np.random.RandomState(0)
    X = rng.randn(300, 5).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), 5)
    pred = bst.to_predictor(warmup=False)
    x1 = X[:1]
    for _ in range(20):
        pred.predict(x1)                       # warm bucket 1
    lats = []
    for _ in range(200):
        t0 = time.perf_counter()
        pred.predict(x1)
        lats.append(time.perf_counter() - t0)
    p50_s = percentile(sorted(lats), 50.0)

    n = 2000
    per_record_s = float("inf")
    for r in range(5):
        t0 = time.perf_counter()
        for i in range(n):
            pred.stats.record_request_timing(1, 1, 0.01, 0.2, 0.25,
                                             request_id=f"ovh-{r}-{i}")
        per_record_s = min(per_record_s, (time.perf_counter() - t0) / n)
    assert per_record_s < 0.05 * p50_s, (per_record_s, p50_s)
