"""Serving-fleet tests (serve/fleet.py): supervision, dispatching and
lifecycle against STUB workers (tier-1: plain-python subprocesses drive
the full process-spawn / port-file / watchdog / breaker / retry
machinery without a jax import), plus slow/chaos acceptance runs with
REAL ``python -m lightgbm_tpu serve`` workers — dispatcher parity with
a direct predictor, chaos-under-load recovery judged from fleet
``/metrics``+``/slo`` scrapes only, the crash-loop breaker, and a
zero-5xx rolling deploy under live load.
"""

import http.client
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serve.fleet import FleetSupervisor
from lightgbm_tpu.serve.loadgen import metric_sum, parse_prometheus

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Minimal worker: answers the fleet's HTTP surface with deterministic
# bodies, honors the chaos knobs through env vars, drains on SIGTERM
# and exits 143 — every supervision path exercised without jax.
STUB_WORKER = r'''
import json, os, signal, sys, threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

PORT_FILE = sys.argv[1]
WID = os.environ.get("STUB_WID", "?")
CRASH_AFTER = int(os.environ.get("STUB_CRASH_AFTER", "0"))
EXIT_FLAG = os.environ.get("STUB_EXIT_FLAG", "")
STATUS = int(os.environ.get("STUB_STATUS", "200"))
DROP_FIRST = int(os.environ.get("STUB_DROP_FIRST", "0"))
MODELS_STATUS = int(os.environ.get("STUB_MODELS_STATUS", "200"))

if EXIT_FLAG and os.path.exists(EXIT_FLAG):
    sys.exit(7)          # crash-loop while the flag file exists

count = [0]
dropped = [0]
models = {}

class H(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    def log_message(self, *a): pass
    def _reply(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
    def do_GET(self):
        if self.path == "/healthz":
            self._reply(200, {"status": "ok", "worker": WID})
        elif self.path == "/models":
            self._reply(200, {n: {"source": p} for n, p in models.items()})
        elif self.path == "/slo":
            self._reply(200, {"schema": "slo-report-v1", "ok": True,
                              "worker": WID})
        elif self.path == "/stats":
            self._reply(200, {"requests": count[0]})
        elif self.path == "/metrics":
            body = ("lgbm_tpu_stub_requests_total %d\n"
                    % count[0]).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._reply(404, {"error": "nope"})
    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        req = json.loads(self.rfile.read(n)) if n else {}
        if self.path == "/predict":
            count[0] += 1
            if CRASH_AFTER and count[0] > CRASH_AFTER:
                os._exit(137)
            if DROP_FIRST and dropped[0] < DROP_FIRST:
                dropped[0] += 1
                import socket as _s
                try:
                    self.connection.shutdown(_s.SHUT_RDWR)
                except OSError:
                    pass
                self.close_connection = True
                return
            if STATUS != 200:
                self._reply(STATUS, {"error": "injected 5xx"})
                return
            self._reply(200, {"worker": WID, "n": count[0],
                              "deadline_ms": req.get("deadline_ms"),
                              "predictions":
                                  [0.5] * len(req.get("rows", []))})
        elif self.path == "/models":
            if MODELS_STATUS != 200:
                self._reply(MODELS_STATUS, {"error": "injected load "
                                                     "failure"})
                return
            models[req["name"]] = req["file"]
            self._reply(200, {"model": req["name"]})
        else:
            self._reply(404, {"error": "nope"})

srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
srv.daemon_threads = True

def _term(signum, frame):
    threading.Thread(target=srv.shutdown, daemon=True).start()

signal.signal(signal.SIGTERM, _term)
tmp = PORT_FILE + ".tmp"
with open(tmp, "w") as fh:
    fh.write(str(srv.server_address[1]))
os.replace(tmp, PORT_FILE)
srv.serve_forever()
sys.exit(143)
'''


def _stub_fleet(tmp_path, workers=2, per_worker_env=None,
                first_spawn_env=None, **kw):
    stub = tmp_path / "stub_worker.py"
    if not stub.exists():
        stub.write_text(STUB_WORKER)
    dummy_model = tmp_path / "model.txt"
    if not dummy_model.exists():
        dummy_model.write_text("stub")
    per_env = {int(k): dict(v) for k, v in (per_worker_env or {}).items()}
    for i in range(workers):
        per_env.setdefault(i, {})
        per_env[i].setdefault("STUB_WID", str(i))
    defaults = dict(
        probe_interval_s=0.1, probe_timeout_s=1.0, hang_probes=3,
        breaker_failures=3, breaker_window_s=10.0,
        breaker_halfopen_s=0.5, probe_ok_needed=2,
        backoff_base_s=0.05, backoff_max_s=0.3,
        startup_timeout_s=60.0, drain_timeout_s=10.0,
        run_dir=str(tmp_path / "fleet-run"))
    defaults.update(kw)
    return FleetSupervisor(
        [str(dummy_model)], workers=workers,
        worker_cmd=lambda wid, port_file: [sys.executable, str(stub),
                                           port_file],
        per_worker_env=per_env, first_spawn_env=first_spawn_env,
        **defaults)


def _post(host, port, path, payload, headers=None, timeout=30):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        body = json.dumps(payload).encode()
        conn.request("POST", path, body, {
            "Content-Type": "application/json",
            "Content-Length": str(len(body)), **(headers or {})})
        r = conn.getresponse()
        data = r.read()
        return r.status, json.loads(data) if data else {}, \
            dict(r.getheaders())
    finally:
        conn.close()


def _get(host, port, path, timeout=30):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        data = r.read()
        return r.status, data
    finally:
        conn.close()


def _get_json(host, port, path, timeout=30):
    status, data = _get(host, port, path, timeout=timeout)
    return status, json.loads(data)


def _scrape(fleet):
    status, data = _get(fleet.host, fleet.port, "/metrics")
    assert status == 200
    return parse_prometheus(data.decode())


def _wait_for(predicate, timeout=20.0, interval=0.05, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


# ---------------------------------------------------------------------------
# tier-1: stub workers through the full supervision/dispatch machinery
# ---------------------------------------------------------------------------

def test_fleet_round_robin_and_deadline_decrement(tmp_path):
    """Health-weighted round-robin spreads traffic over both workers,
    the dispatch hop decrements deadline_ms before forwarding, and the
    X-Request-Id is echoed end to end."""
    fleet = _stub_fleet(tmp_path, workers=2).start()
    try:
        seen = set()
        for i in range(8):
            status, body, headers = _post(
                fleet.host, fleet.port, "/predict",
                {"rows": [[1.0, 2.0]], "deadline_ms": 5000},
                headers={"X-Request-Id": f"rr-{i}"})
            assert status == 200, body
            seen.add(body["worker"])
            assert 0 < body["deadline_ms"] < 5000
            assert headers.get("X-Request-Id") == f"rr-{i}"
        assert seen == {"0", "1"}, "round-robin never reached a worker"
        status, health = _get_json(fleet.host, fleet.port, "/healthz")
        assert status == 200 and health["status"] == "ok"
        assert health["workers_alive"] == 2
    finally:
        fleet.shutdown()


def test_fleet_crash_restart_under_traffic(tmp_path):
    """A worker hard-killed mid-stream costs the client NOTHING: the
    reset request is retried on the other worker, the supervisor
    restarts the dead one, and the fleet metrics record both."""
    fleet = _stub_fleet(
        tmp_path, workers=2,
        first_spawn_env={0: {"STUB_CRASH_AFTER": "3"}}).start()
    try:
        for i in range(20):
            status, body, _ = _post(fleet.host, fleet.port, "/predict",
                                    {"rows": [[1.0]]})
            assert status == 200, (i, body)
        _wait_for(lambda: all(w.state == "alive"
                              for w in fleet.workers()),
                  desc="both workers alive again")
        parsed = _scrape(fleet)
        assert metric_sum(parsed, "lgbm_tpu_fleet_restarts_total") >= 1
        assert metric_sum(parsed, "lgbm_tpu_fleet_retries_total") >= 1
        assert metric_sum(parsed, "lgbm_tpu_fleet_workers_alive") == 2
        # replacement worker boots WITHOUT the first-spawn chaos env
        for i in range(10):
            status, _, _ = _post(fleet.host, fleet.port, "/predict",
                                 {"rows": [[1.0]]})
            assert status == 200
    finally:
        fleet.shutdown()


def test_fleet_crash_loop_breaker_and_half_open(tmp_path):
    """K failures in the window open the breaker: the worker is
    quarantined instead of restart-storming, /predict fast-fails 503 +
    Retry-After, /healthz goes degraded; once the fault clears, the
    half-open probe restores the worker and closes the breaker."""
    flag = tmp_path / "crash.flag"
    fleet = _stub_fleet(
        tmp_path, workers=1,
        per_worker_env={0: {"STUB_EXIT_FLAG": str(flag)}}).start()
    try:
        flag.write_text("on")          # every respawn now dies at boot
        w = fleet.workers()[0]
        assert w.proc is not None
        w.proc.kill()                  # trigger the first failure
        _wait_for(lambda: w.state == "quarantined",
                  desc="breaker open")
        assert len(w.fail_times) >= 3  # K failures, then no storm
        restarts_at_open = w.restarts
        status, body, headers = _post(fleet.host, fleet.port,
                                      "/predict", {"rows": [[1.0]]})
        assert status == 503
        assert "Retry-After" in headers
        status, health = _get_json(fleet.host, fleet.port, "/healthz")
        assert health["status"] == "degraded"
        assert any("breaker" in r for r in health["reasons"])
        parsed = _scrape(fleet)
        assert metric_sum(parsed,
                          "lgbm_tpu_fleet_workers_quarantined") == 1
        assert metric_sum(parsed, "lgbm_tpu_fleet_workers_alive") == 0

        flag.unlink()                  # fault cleared: half-open probe
        _wait_for(lambda: w.state == "alive" and not w.probing and
                  len(w.fail_times) == 0,
                  desc="breaker closed after a clean probe")
        assert w.restarts <= restarts_at_open + 2   # probe, not storm
        status, body, _ = _post(fleet.host, fleet.port, "/predict",
                                {"rows": [[1.0]]})
        assert status == 200 and body["worker"] == "0"
    finally:
        fleet.shutdown()


def test_fleet_5xx_forwarded_never_retried(tmp_path):
    """A 5xx that REACHED a predictor is the worker's answer — the
    dispatcher forwards it verbatim and spends no retry budget on it."""
    fleet = _stub_fleet(
        tmp_path, workers=2,
        per_worker_env={0: {"STUB_STATUS": "500"}}).start()
    try:
        codes = []
        for _ in range(8):
            status, _, _ = _post(fleet.host, fleet.port, "/predict",
                                 {"rows": [[1.0]]})
            codes.append(status)
        assert 500 in codes and 200 in codes, codes
        parsed = _scrape(fleet)
        assert metric_sum(parsed, "lgbm_tpu_fleet_retries_total") == 0
    finally:
        fleet.shutdown()


def test_fleet_dropped_connection_retried_on_other_worker(tmp_path):
    """A connection severed before any response (the serve_drop_conn
    class) is retried against a DIFFERENT worker inside the budget —
    the client sees one 200, the fleet counts one retry."""
    fleet = _stub_fleet(
        tmp_path, workers=2,
        per_worker_env={0: {"STUB_DROP_FIRST": "1"},
                        1: {"STUB_WID": "1"}}).start()
    try:
        outcomes = []
        for _ in range(6):
            status, body, _ = _post(fleet.host, fleet.port, "/predict",
                                    {"rows": [[1.0]]})
            outcomes.append((status, body.get("worker")))
        assert all(s == 200 for s, _ in outcomes), outcomes
        parsed = _scrape(fleet)
        assert metric_sum(parsed, "lgbm_tpu_fleet_retries_total") >= 1
    finally:
        fleet.shutdown()


def test_fleet_metrics_and_slo_aggregate_worker_scrapes(tmp_path):
    """Fleet /metrics carries the supervision series AND each worker's
    scrape re-labeled worker=wN; /slo wraps the fleet verdict with the
    per-worker reports."""
    fleet = _stub_fleet(tmp_path, workers=2).start()
    try:
        _post(fleet.host, fleet.port, "/predict", {"rows": [[1.0]]})
        parsed = _scrape(fleet)
        assert metric_sum(parsed, "lgbm_tpu_fleet_workers_alive") == 2
        assert metric_sum(
            parsed, "lgbm_tpu_serve_predict_responses_total",
            code="200") >= 1
        per_worker = parsed.get("lgbm_tpu_worker_stub_requests_total",
                                [])
        assert {lbl.get("worker") for lbl, _ in per_worker} == \
            {"w0", "w1"}
        # declared fleet SLOs evaluate against the fleet registry
        assert metric_sum(parsed, "lgbm_tpu_slo_burn_rate",
                          slo="fleet/workers_alive", window="fast") == 0
        status, slo_rep = _get_json(fleet.host, fleet.port, "/slo")
        assert status == 200
        assert slo_rep["schema"] == "fleet-slo-report-v1"
        assert slo_rep["ok"] is True
        assert set(slo_rep["workers"]) == {"w0", "w1"}
        names = {s["name"] for s in slo_rep["fleet"]["slos"]}
        assert {"fleet/workers_alive", "fleet/retry_rate"} <= names
    finally:
        fleet.shutdown()


def test_fleet_rolling_deploy_stub_order_and_abort(tmp_path):
    """The roll walks workers in order; a worker that rejects the new
    version aborts the roll with the already-swapped workers reported
    (its own old version was never touched — registry load fails before
    any swap)."""
    new_file = tmp_path / "model_v2.txt"
    new_file.write_text("stub v2")
    fleet = _stub_fleet(tmp_path, workers=2).start()
    try:
        status, report, _ = _post(fleet.host, fleet.port, "/models",
                                  {"name": "m", "file": str(new_file)})
        assert status == 200, report
        assert report["verdict"] == "deployed"
        assert report["deployed"] == ["w0", "w1"]
    finally:
        fleet.shutdown()

    fleet = _stub_fleet(tmp_path, workers=2,
                        per_worker_env={
                            1: {"STUB_MODELS_STATUS": "500"}}).start()
    try:
        status, report, _ = _post(fleet.host, fleet.port, "/models",
                                  {"name": "m", "file": str(new_file)})
        assert status == 409
        assert report["verdict"] == "aborted"
        assert report["deployed"] == ["w0"]
        assert "w1" in report["error"]
    finally:
        fleet.shutdown()


def test_fleet_deploy_survives_worker_respawn(tmp_path):
    """A deployed version whose file name does not spell the logical
    model name must still be served by a crash-restarted worker: the
    supervisor records the deploy in _current_models (new names too)
    and catches the respawned worker up over POST /models — without
    this, the first crash after a deploy serves 404s for the deployed
    name."""
    stub = tmp_path / "stub_worker.py"
    stub.write_text(STUB_WORKER)
    model_a = tmp_path / "m_a.txt"
    model_a.write_text("stub a")
    model_b = tmp_path / "m_b.txt"
    model_b.write_text("stub b")
    v2 = tmp_path / "m_a_v2.txt"      # renamed source: basename-derived
    v2.write_text("stub a v2")        # name "m_a_v2" != logical "m_a"
    fleet = FleetSupervisor(
        [str(model_a), str(model_b)], workers=2,
        worker_cmd=lambda wid, port_file: [sys.executable, str(stub),
                                           port_file],
        per_worker_env={0: {"STUB_WID": "0"}, 1: {"STUB_WID": "1"}},
        probe_interval_s=0.1, backoff_base_s=0.05, backoff_max_s=0.3,
        breaker_failures=5, breaker_window_s=10.0,
        startup_timeout_s=60.0, drain_timeout_s=10.0,
        run_dir=str(tmp_path / "fleet-run")).start()
    try:
        status, report, _ = _post(fleet.host, fleet.port, "/models",
                                  {"name": "m_a", "file": str(v2)})
        assert status == 200 and report["verdict"] == "deployed", report
        # kill w0; the respawned stub boots with an empty model table
        w0 = fleet.workers()[0]
        first_pid = w0.proc.pid
        w0.proc.kill()
        _wait_for(lambda: w0.state == "alive" and
                  w0.proc.pid != first_pid and
                  w0.synced_incarnation == w0.incarnation,
                  desc="w0 respawned and model-synced")
        status, models = _get_json(fleet.host, fleet.port, "/models")
        assert status == 200
        assert models["w0"].get("m_a", {}).get("source") == str(v2), \
            models["w0"]
    finally:
        fleet.shutdown()


def test_fleet_shutdown_is_a_rolling_drain(tmp_path):
    """shutdown() SIGTERMs workers one at a time; each drains and exits
    143 (128+SIGTERM), and the dispatcher socket closes last."""
    fleet = _stub_fleet(tmp_path, workers=2).start()
    port = fleet.port
    procs = [w.proc for w in fleet.workers()]
    fleet.shutdown()
    for p in procs:
        assert p is not None and p.poll() == 143, \
            f"worker exit code {p.poll() if p else None}"
    with pytest.raises(OSError):
        _get(fleet.host, port, "/healthz", timeout=2)
    fleet.shutdown()   # idempotent


# ---------------------------------------------------------------------------
# slow/chaos: real `python -m lightgbm_tpu serve` workers
# ---------------------------------------------------------------------------

SMALL = {"num_leaves": 7, "min_data_in_leaf": 5, "verbosity": -1}


@pytest.fixture(scope="module")
def fleet_booster(binary_data):
    X, y = binary_data
    p = {**SMALL, "objective": "binary"}
    return lgb.train(p, lgb.Dataset(X, y, params=p), 15)


def _real_fleet(tmp_path, model_file, workers=2, **kw):
    defaults = dict(
        worker_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO},
        worker_args={"warmup": "0", "max_wait_ms": "0.5"},
        probe_interval_s=0.25, probe_timeout_s=5.0,
        breaker_failures=3, breaker_window_s=20.0,
        breaker_halfopen_s=1.0,
        backoff_base_s=0.2, backoff_max_s=1.0,
        startup_timeout_s=180.0, drain_timeout_s=30.0,
        forward_timeout_s=60.0,
        run_dir=str(tmp_path / "fleet-run"))
    defaults.update(kw)
    return FleetSupervisor([model_file], workers=workers, **defaults)


@pytest.mark.slow
def test_fleet_parity_with_direct_predictor(tmp_path, binary_data,
                                            fleet_booster):
    """Acceptance: predictions routed through the dispatcher are
    bit-identical to a direct single-worker PredictionServer and to
    Booster.predict, across bucket boundaries (floats round-trip JSON
    via repr, so equality is exact)."""
    from lightgbm_tpu.serve import ModelRegistry, PredictionServer
    X, _ = binary_data
    model_file = str(tmp_path / "model.txt")
    fleet_booster.save_model(model_file)
    reg = ModelRegistry()
    reg.load("model", model_file, warmup=False)
    direct = PredictionServer(reg, port=0, max_wait_ms=0.5).start()
    fleet = _real_fleet(tmp_path, model_file, workers=2).start()
    try:
        rng = np.random.RandomState(0)
        for n in (1, 7, 8, 9, 511, 513):
            Xq = rng.randn(n, X.shape[1]).astype(np.float32)
            ref = fleet_booster.predict(Xq).tolist()
            st_f, body_f, _ = _post(fleet.host, fleet.port, "/predict",
                                    {"rows": Xq.tolist()}, timeout=120)
            st_d, body_d, _ = _post(direct.host, direct.port,
                                    "/predict", {"rows": Xq.tolist()},
                                    timeout=120)
            assert st_f == 200 and st_d == 200
            assert body_f["predictions"] == ref, f"n={n}: fleet drift"
            assert body_d["predictions"] == ref, f"n={n}: direct drift"
    finally:
        fleet.shutdown()
        direct.shutdown()


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_chaos_under_load(tmp_path, fleet_booster):
    """Acceptance: a 4-worker fleet under loadgen traffic survives
    repeated worker kills — every client request gets a terminal
    response, the fleet returns to full strength, and the verdict
    (availability SLO met after the recovery window, restarts recorded)
    is read from fleet /metrics + /slo scrapes only."""
    from lightgbm_tpu.serve.loadgen import LoadGenerator, LoadSpec
    model_file = str(tmp_path / "model.txt")
    fleet_booster.save_model(model_file)
    fleet = _real_fleet(tmp_path, model_file, workers=4).start()
    try:
        spec = LoadSpec(duration_s=6.0, target_qps=40.0, workers=2,
                        features=6, bucket_mix={8: 1.0}, seed=3,
                        timeout_s=30.0)
        gen = LoadGenerator(fleet.host, fleet.port, spec)
        kills = []

        def killer():
            for i in (0, 2):
                time.sleep(1.5)
                w = fleet.workers()[i]
                if w.proc is not None and w.proc.poll() is None:
                    w.proc.kill()
                    kills.append(w.name)

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        client = gen.run()
        kt.join(20)
        assert len(kills) == 2
        # every request the generator fired reached a terminal outcome
        # (a code or a counted connection failure) — no hangs
        terminal = sum(client.by_code.values()) + client.connect_errors
        assert terminal == client.requests_sent
        assert client.by_code.get(200, 0) > 0
        # recovery: full strength within the recovery window
        _wait_for(lambda: all(w.state == "alive"
                              for w in fleet.workers()),
                  timeout=60.0, desc="fleet back to 4 alive workers")
        # the verdict inputs: fleet scrapes only
        parsed = _scrape(fleet)
        assert metric_sum(parsed, "lgbm_tpu_fleet_restarts_total") >= 2
        assert metric_sum(parsed, "lgbm_tpu_fleet_workers_alive") == 4
        total = metric_sum(parsed,
                           "lgbm_tpu_serve_predict_responses_total")
        bad = sum(metric_sum(parsed,
                             "lgbm_tpu_serve_predict_responses_total",
                             code=c) for c in ("500", "502", "503",
                                               "504"))
        assert total > 0
        assert bad / total <= 0.05, (bad, total)
        status, slo_rep = _get_json(fleet.host, fleet.port, "/slo")
        assert status == 200 and slo_rep["ok"] is True, slo_rep
    finally:
        fleet.shutdown()


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_breaker_quarantines_crash_looping_worker(tmp_path,
                                                        fleet_booster):
    """Acceptance: a worker armed to crash on (almost) every request —
    serve_crash_after_n on EVERY incarnation — opens the breaker within
    K failures instead of restart-storming, while the healthy worker
    keeps answering."""
    model_file = str(tmp_path / "model.txt")
    fleet_booster.save_model(model_file)
    fleet = _real_fleet(
        tmp_path, model_file, workers=2,
        breaker_halfopen_s=300.0,   # keep it open for the assertion
        per_worker_env={1: {"LGBM_TPU_FAULTS":
                            "serve_crash_after_n=1"}}).start()
    try:
        w1 = fleet.workers()[1]
        deadline = time.monotonic() + 120.0
        while w1.state != "quarantined" and time.monotonic() < deadline:
            status, _, _ = _post(fleet.host, fleet.port, "/predict",
                                 {"rows": [[0.0] * 6]}, timeout=60)
            assert status in (200, 502), status
            time.sleep(0.05)
        assert w1.state == "quarantined", w1.snapshot()
        # breaker, not a storm: K failures -> quarantine, restarts
        # bounded by K (plus the initial spawn)
        assert w1.restarts <= 3, w1.snapshot()
        parsed = _scrape(fleet)
        assert metric_sum(parsed,
                          "lgbm_tpu_fleet_workers_quarantined") == 1
        status, health = _get_json(fleet.host, fleet.port, "/healthz")
        assert health["status"] == "degraded"
        # the healthy worker still answers
        status, _, _ = _post(fleet.host, fleet.port, "/predict",
                             {"rows": [[0.0] * 6]}, timeout=60)
        assert status == 200
    finally:
        fleet.shutdown()


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_rolling_deploy_zero_5xx_under_load(tmp_path, binary_data,
                                                  fleet_booster):
    """Acceptance: hot-swapping a model version across the fleet under
    live loadgen traffic serves ZERO 5xx attributable to the deploy —
    old or new version answers every request during the roll — and the
    fleet serves the new version afterwards."""
    from lightgbm_tpu.serve.loadgen import LoadGenerator, LoadSpec
    X, y = binary_data
    p = {**SMALL, "objective": "binary"}
    b2 = lgb.train(p, lgb.Dataset(X, y, params=p), 9)
    model_file = str(tmp_path / "model.txt")
    v2_file = str(tmp_path / "model_v2.txt")
    fleet_booster.save_model(model_file)
    b2.save_model(v2_file)
    fleet = _real_fleet(tmp_path, model_file, workers=2).start()
    try:
        spec = LoadSpec(duration_s=5.0, target_qps=30.0, workers=2,
                        features=6, bucket_mix={8: 1.0}, seed=5)
        gen = LoadGenerator(fleet.host, fleet.port, spec)
        deploy_result = {}

        def deployer():
            time.sleep(1.5)
            status, report, _ = _post(
                fleet.host, fleet.port, "/models",
                {"name": "model", "file": v2_file}, timeout=120)
            deploy_result["status"] = status
            deploy_result["report"] = report

        dt = threading.Thread(target=deployer, daemon=True)
        dt.start()
        client = gen.run()
        dt.join(120)
        assert deploy_result.get("status") == 200, deploy_result
        assert deploy_result["report"]["verdict"] == "deployed"
        assert deploy_result["report"]["deployed"] == ["w0", "w1"]
        # zero 5xx through the roll, client side AND fleet side
        bad_client = sum(v for c, v in client.by_code.items()
                         if c >= 500)
        assert bad_client == 0 and client.connect_errors == 0, \
            client.summary()
        parsed = _scrape(fleet)
        bad = sum(metric_sum(parsed,
                             "lgbm_tpu_serve_predict_responses_total",
                             code=c) for c in ("500", "502", "503",
                                               "504"))
        assert bad == 0
        # the fleet now answers with the NEW version
        ref = b2.predict(X[:1]).tolist()
        status, body, _ = _post(fleet.host, fleet.port, "/predict",
                                {"rows": X[:1].tolist()}, timeout=60)
        assert status == 200 and body["predictions"] == ref
    finally:
        fleet.shutdown()
