"""True multi-PROCESS distributed training on localhost (the reference's
test_dask.py pattern: an in-process multi-worker cluster per test run,
each worker doing a real network init, results asserted ≈ serial).

Here each worker is a separate OS process running the same SPMD driver:
``lightgbm_tpu.distributed.init`` forms the JAX multi-process runtime
(gloo collectives on CPU), the data-parallel learner's mesh spans both
processes' devices, and the resulting model must match single-process
training exactly.  Every non-slow suite shares ONE 2-process world (a
module-scoped fixture): each extra worker-pair launch costs a full jax
import + gloo init on CI, so the data-learner, wave, voting and
pre-partition suites all train inside the same pair of processes."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from conftest import FP_SKIP

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# one world, every non-slow cross-process suite: data learner (masked
# grower), quantized wave grower, voting-parallel learner, then the
# pre_partition shard suites (dense binary, sparse, linear trees)
_WORKER = textwrap.dedent("""
    import sys
    rank = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    sys.path.insert(0, {repo!r})
    import os
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:  # older jax: XLA_FLAGS is the portable spelling
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=2").strip()
    import lightgbm_tpu as lgb
    lgb.distributed.init(coordinator_address="127.0.0.1:" + port,
                         num_processes=2, process_id=rank)
    import numpy as np
    import scipy.sparse as sp
    from lightgbm_tpu.utils.log import set_verbosity
    set_verbosity(-1)
    rng = np.random.RandomState(11)
    n = 700
    X = rng.randn(n, 6)
    y = ((X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 * 0.2) > 0).astype(float)
    P = {{"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
          "verbosity": -1, "tree_learner": "data"}}
    # the wave grower (quantized, deterministic rounding) cross-process
    # before the masked-grower run
    PW = dict(P, tree_grow_mode="wave", use_quantized_grad=True,
              stochastic_rounding=False, quant_train_renew_leaf=True)
    bw = lgb.train(PW, lgb.Dataset(X, y), 3)
    np.save(f"{{outdir}}/wpred_{{rank}}.npy", bw.predict(X))
    # the voting-parallel learner in the SAME world
    bv = lgb.train(dict(P, tree_learner="voting"), lgb.Dataset(X, y), 5)
    np.save(f"{{outdir}}/vpred_{{rank}}.npy", bv.predict(X))
    bst = lgb.train(P, lgb.Dataset(X, y), 5)
    np.save(f"{{outdir}}/pred_{{rank}}.npy", bst.predict(X))

    # dense pre_partition: disjoint binary shards must reproduce
    # full-data training
    lo, hi = (0, 350) if rank == 0 else (350, 700)
    PP = dict(P, pre_partition=True)
    bst = lgb.train(PP, lgb.Dataset(X[lo:hi], y[lo:hi]), 5)
    np.save(f"{{outdir}}/ppred_{{rank}}.npy", bst.predict(X))

    # sparse shards + linear trees, still the same 2-process world
    rng = np.random.RandomState(23)
    n = 800
    X = rng.randn(n, 6)
    y = (X[:, 0] * 2 - X[:, 1] + 0.3 * rng.randn(n))
    lo, hi = (0, 400) if rank == 0 else (400, 800)
    PR = {{"objective": "regression", "num_leaves": 7, "min_data_in_leaf": 5,
           "verbosity": -1, "tree_learner": "data", "pre_partition": True}}
    Xs = X.copy(); Xs[np.abs(Xs) < 0.6] = 0.0
    local = sp.csr_matrix(Xs[lo:hi])
    bst = lgb.train(PR, lgb.Dataset(local, y[lo:hi]), 5)
    np.save(f"{{outdir}}/spred_{{rank}}.npy", bst.predict(Xs))
    PL = dict(PR, linear_tree=True)
    bst = lgb.train(PL, lgb.Dataset(X[lo:hi], y[lo:hi]), 5)
    np.save(f"{{outdir}}/lpred_{{rank}}.npy", bst.predict(X))
""")

# feature-parallel only (skipped until the env's jax grows shard_map) —
# kept out of the shared world so the shared launch never depends on it
_WORKER_FP = textwrap.dedent("""
    import sys
    rank = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    sys.path.insert(0, {repo!r})
    import os
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=2").strip()
    import lightgbm_tpu as lgb
    lgb.distributed.init(coordinator_address="127.0.0.1:" + port,
                         num_processes=2, process_id=rank)
    import numpy as np
    from lightgbm_tpu.utils.log import set_verbosity
    set_verbosity(-1)
    rng = np.random.RandomState(11)
    n = 700
    X = rng.randn(n, 6)
    y = ((X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 * 0.2) > 0).astype(float)
    P = {{"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
          "verbosity": -1, "tree_learner": "feature"}}
    bst = lgb.train(P, lgb.Dataset(X, y), 5)
    np.save(f"{{outdir}}/fpred_{{rank}}.npy", bst.predict(X))
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_pair(script_body, outdir):
    script = os.path.join(str(outdir), "worker.py")
    with open(script, "w") as fh:
        fh.write(script_body.format(repo=REPO))
    port = str(_free_port())
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="")
    procs = [subprocess.Popen(
        [sys.executable, script, str(r), port, str(outdir)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=420)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    """The shared 2-process gloo world: launched once, every non-slow
    suite's predictions saved under the returned directory."""
    outdir = tmp_path_factory.mktemp("mpworld")
    _launch_pair(_WORKER, outdir)
    return outdir


def _serial_binary(rounds=5):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(11)
    n = 700
    X = rng.randn(n, 6)
    y = ((X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 * 0.2) > 0).astype(float)
    pred = lgb.train({"objective": "binary", "num_leaves": 7,
                      "min_data_in_leaf": 5, "verbosity": -1},
                     lgb.Dataset(X, y), rounds).predict(X)
    return pred


def test_two_process_training_matches_serial(world):
    p0 = np.load(world / "pred_0.npy")
    p1 = np.load(world / "pred_1.npy")
    np.testing.assert_allclose(p0, p1, atol=1e-7)  # ranks agree exactly
    w0 = np.load(world / "wpred_0.npy")
    w1 = np.load(world / "wpred_1.npy")
    np.testing.assert_allclose(w0, w1, atol=1e-7)
    assert np.isfinite(w0).all()
    v0 = np.load(world / "vpred_0.npy")
    v1 = np.load(world / "vpred_1.npy")
    np.testing.assert_allclose(v0, v1, atol=1e-7)  # ranks agree

    # serial baseline in THIS process (8-device mesh, single process)
    serial = _serial_binary()
    np.testing.assert_allclose(p0, serial, atol=2e-5)
    np.testing.assert_allclose(v0, serial, atol=2e-5)


@FP_SKIP
def test_two_process_feature_learner_matches_serial(tmp_path):
    _launch_pair(_WORKER_FP, tmp_path)
    p0 = np.load(tmp_path / "fpred_0.npy")
    p1 = np.load(tmp_path / "fpred_1.npy")
    np.testing.assert_allclose(p0, p1, atol=1e-7)
    np.testing.assert_allclose(p0, _serial_binary(), atol=2e-5)


def test_two_process_pre_partition_dense_sparse_linear(world):
    """Disjoint per-process shards (pre_partition) + distributed bin
    finding reproduce full-data training (dataset_loader.cpp:1040's
    per-rank FindBin + allgather contract) — dense binary shards exactly,
    plus sparse shards (gathered nonzero samples + global zero fractions)
    and linear trees (row-sharded raw matrix) in the same world."""
    p0 = np.load(world / "ppred_0.npy")
    p1 = np.load(world / "ppred_1.npy")
    np.testing.assert_allclose(p0, p1, atol=1e-7)
    np.testing.assert_allclose(p0, _serial_binary(), atol=2e-4)

    # sparse + linear: ranks agree, quality sanity vs the targets
    # (mappers differ slightly from serial sampling, so exact-serial
    # parity is not asserted here)
    rng = np.random.RandomState(23)
    n = 800
    X = rng.randn(n, 6)
    y = (X[:, 0] * 2 - X[:, 1] + 0.3 * rng.randn(n))
    for tag in ("spred", "lpred"):
        p0 = np.load(world / f"{tag}_0.npy")
        p1 = np.load(world / f"{tag}_1.npy")
        np.testing.assert_allclose(p0, p1, atol=1e-6)  # ranks agree
        assert np.isfinite(p0).all()
        assert np.mean((p0 - y) ** 2) < np.var(y) * 0.6


# -- chaos: one worker of a collective dies mid-train ------------------------
_WORKER_CHAOS = textwrap.dedent("""
    import sys
    rank = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    resume = sys.argv[4] == "resume"
    sys.path.insert(0, {repo!r})
    import os
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", 2)
    except AttributeError:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
            " --xla_force_host_platform_device_count=2").strip()
    import lightgbm_tpu as lgb
    lgb.distributed.init(coordinator_address="127.0.0.1:" + port,
                         num_processes=2, process_id=rank)
    import numpy as np
    from lightgbm_tpu.utils.log import set_verbosity
    set_verbosity(-1)
    rng = np.random.RandomState(11)
    n = 700
    X = rng.randn(n, 6)
    y = ((X[:, 0] + 0.5 * X[:, 1] - X[:, 2] ** 2 * 0.2) > 0).astype(float)
    P = {{"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
          "verbosity": -1, "tree_learner": "data",
          "checkpoint_dir": f"{{outdir}}/ck_{{rank}}"}}
    if resume:
        P["resume"] = "latest"
    bst = lgb.train(P, lgb.Dataset(X, y), 6)
    np.save(f"{{outdir}}/cpred_{{rank}}.npy", bst.predict(X))
""")


@pytest.mark.slow
@pytest.mark.chaos
def test_worker_killed_mid_collective_job_resumes(tmp_path):
    """PV-Tree-regime chaos (resilience/faults.py kill_at_iter+kill_rank):
    rank 1 of a 2-process data-parallel run is hard-killed entering
    iteration 3 — the host-side analogue of a preempted worker dying
    mid-allreduce.  The orchestrator (this test) reaps the survivor and
    relaunches the job with resume=latest; the resumed job completes
    from the checkpoint ring and reproduces serial training."""
    script = str(tmp_path / "worker_chaos.py")
    with open(script, "w") as fh:
        fh.write(_WORKER_CHAOS.format(repo=REPO))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="", LGBM_TPU_FAULTS="kill_at_iter=3,kill_rank=1")
    port = str(_free_port())
    procs = [subprocess.Popen(
        [sys.executable, script, str(r), port, str(tmp_path), "fresh"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    out1 = procs[1].communicate(timeout=420)[0].decode()
    assert procs[1].returncode == 137, f"rank1 should die killed:\n{out1[-2000:]}"
    # the survivor is stuck in (or erroring out of) a collective whose
    # peer vanished; a real orchestrator reaps and reschedules the job
    procs[0].kill()
    procs[0].communicate(timeout=60)
    ck1 = tmp_path / "ck_1"
    assert ck1.is_dir() and any(f.startswith("ckpt_iter")
                                for f in os.listdir(ck1))

    env_resume = dict(os.environ, JAX_PLATFORMS="cpu",
                      PALLAS_AXON_POOL_IPS="", XLA_FLAGS="")
    port = str(_free_port())
    procs = [subprocess.Popen(
        [sys.executable, script, str(r), port, str(tmp_path), "resume"],
        env=env_resume, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=420)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"resumed worker failed:\n{out[-3000:]}"
    p0 = np.load(tmp_path / "cpred_0.npy")
    p1 = np.load(tmp_path / "cpred_1.npy")
    np.testing.assert_allclose(p0, p1, atol=1e-7)

    serial = _serial_binary(rounds=6)
    np.testing.assert_allclose(p0, serial, atol=2e-5)
