// Native LGBM_* ABI shim: real extern "C" symbols with the reference's
// out-pointer calling convention (reference: include/LightGBM/c_api.h),
// backed by this framework's in-process Python surface
// (lightgbm_tpu/capi.py) through an embedded CPython interpreter.
//
// Design: every exported function is a thin relay — scalars, strings and
// RAW POINTER ADDRESSES cross into a Python helper prelude (defined
// below) which wraps the addresses with ctypes+numpy, calls
// lightgbm_tpu.capi, and writes results back through the caller's out
// pointers.  Handles are the Python registry's integer ids cast to
// void*.  The -1 + LGBM_GetLastError error contract is preserved
// (strict ABI mode scoped around each helper call, so the in-process
// Python capi's raise-by-default mode is untouched).
//
// Lifecycle: if a Python interpreter already exists in the process (the
// common embedding test: ctypes.CDLL from Python), it is reused via
// PyGILState; otherwise one is initialized and its GIL released so any
// thread may call in.
//
// Build: utils/native.py build_capi_shim() —
//   g++ -O2 -shared -fPIC capi_shim.cc $(python3-config --includes
//   --ldflags --embed) -o liblightgbm_tpu_capi.so

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

namespace {

std::mutex g_mu;
PyObject* g_helpers = nullptr;  // module dict holding the prelude
// thread-local like the reference's last-error storage, so concurrent
// callers never race on the message buffer
thread_local std::string g_last_error = "ok";

const char* safe_utf8(PyObject* s, const char* fallback) {
  const char* c = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (c == nullptr) {
    PyErr_Clear();
    return fallback;
  }
  return c;
}

const char kPrelude[] = R"PY(
import ctypes
import numpy as np
import lightgbm_tpu.capi as capi

_DT = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}


def _wrap(fn):
    """-1 codes for the C surface: exceptions are caught HERE, so the
    in-process Python capi keeps its raise-by-default mode untouched
    (no global flag flip; safe under concurrent in-process users)."""
    def inner(*args):
        try:
            return fn(*args)
        except Exception as e:  # noqa: BLE001 — the ABI swallows into -1
            capi._last_error[0] = f"{type(e).__name__}: {e}"
            return (-1, 0, 0)
    return inner


def _mat(addr, data_type, nrow, ncol, is_row_major):
    n = int(nrow) * int(ncol)
    dt = _DT[int(data_type)]
    buf = (ctypes.c_char * (n * np.dtype(dt).itemsize)).from_address(addr)
    a = np.frombuffer(buf, dtype=dt, count=n)
    return a.reshape((nrow, ncol)) if is_row_major else \
        a.reshape((ncol, nrow)).T


def _vec(addr, data_type, n):
    dt = _DT[int(data_type)]
    buf = (ctypes.c_char * (int(n) * np.dtype(dt).itemsize)).from_address(
        addr)
    return np.frombuffer(buf, dtype=dt, count=int(n))


def _err():
    return capi.LGBM_GetLastError()


def dataset_from_mat(addr, data_type, nrow, ncol, is_row_major, params,
                     ref):
    X = np.array(_mat(addr, data_type, nrow, ncol, is_row_major),
                 np.float64)
    code, h = capi.LGBM_DatasetCreateFromMat(
        X, params, reference=(ref or None))
    return code, (h or 0)


def dataset_set_field(handle, name, addr, num_element, data_type):
    v = np.array(_vec(addr, data_type, num_element))
    code, _ = capi.LGBM_DatasetSetField(handle, name, v)
    return code, 0


def dataset_free(handle):
    code, _ = capi.LGBM_DatasetFree(handle)
    return code, 0


def booster_create(train_handle, params):
    code, h = capi.LGBM_BoosterCreate(train_handle, params)
    return code, (h or 0)


def booster_from_modelfile(filename):
    code, h = capi.LGBM_BoosterCreateFromModelfile(filename)
    if code != 0:
        return code, 0, 0
    code2, it = capi.LGBM_BoosterGetCurrentIteration(h)
    return code, (h or 0), (it or 0)


def booster_update(handle):
    code, fin = capi.LGBM_BoosterUpdateOneIter(handle)
    return code, int(bool(fin))


def booster_save(handle, start_iteration, num_iteration, filename):
    code, _ = capi.LGBM_BoosterSaveModel(handle, filename,
                                         start_iteration, num_iteration)
    return code, 0


def booster_free(handle):
    code, _ = capi.LGBM_BoosterFree(handle)
    return code, 0


def booster_predict_into(handle, addr, data_type, nrow, ncol,
                         is_row_major, predict_type, start_iteration,
                         num_iteration, out_addr):
    X = np.array(_mat(addr, data_type, nrow, ncol, is_row_major),
                 np.float64)
    code, out = capi.LGBM_BoosterPredictForMat(
        handle, X, predict_type, start_iteration, num_iteration)
    if code != 0:
        return code, 0
    out = np.atleast_1d(np.asarray(out, np.float64)).ravel()
    np.copyto(_vec(out_addr, 1, len(out)), out)
    return 0, len(out)


for _n in ("dataset_from_mat", "dataset_set_field", "dataset_free",
           "booster_create", "booster_from_modelfile", "booster_update",
           "booster_save", "booster_free", "booster_predict_into"):
    globals()[_n] = _wrap(globals()[_n])
)PY";

// Run one helper and unpack its (code, value...) tuple.  Caller holds
// the GIL.
PyObject* call_helper(const char* name, PyObject* args) {
  PyObject* fn = PyDict_GetItemString(g_helpers, name);  // borrowed
  if (fn == nullptr) {
    g_last_error = std::string("helper missing: ") + name;
    return nullptr;
  }
  PyObject* res = PyObject_CallObject(fn, args);
  if (res == nullptr) {
    PyObject *t, *v, *tb;
    PyErr_Fetch(&t, &v, &tb);
    PyObject* s = v ? PyObject_Str(v) : nullptr;
    g_last_error = safe_utf8(s, "python exception");
    Py_XDECREF(s);
    Py_XDECREF(t);
    Py_XDECREF(v);
    Py_XDECREF(tb);
    return nullptr;
  }
  return res;
}

bool fetch_py_error() {
  // after a strict-ABI -1 the message lives in capi.LGBM_GetLastError
  PyObject* args = PyTuple_New(0);
  PyObject* res = call_helper("_err", args);
  Py_DECREF(args);
  if (res != nullptr) {
    if (PyUnicode_Check(res))
      g_last_error = safe_utf8(res, "unavailable error message");
    Py_DECREF(res);
  }
  return true;
}

int ensure_python() {
  std::lock_guard<std::mutex> lk(g_mu);
  static bool owns_interp = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    owns_interp = true;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 0;
  if (g_helpers == nullptr) {
    PyObject* mod = PyModule_New("lightgbm_tpu_capi_shim");
    PyObject* dict = PyModule_GetDict(mod);
    PyDict_SetItemString(dict, "__builtins__", PyEval_GetBuiltins());
    PyObject* res = PyRun_String(kPrelude, Py_file_input, dict, dict);
    if (res == nullptr) {
      PyObject *t, *v, *tb;
      PyErr_Fetch(&t, &v, &tb);
      PyObject* s = v ? PyObject_Str(v) : nullptr;
      g_last_error = safe_utf8(
          s, "failed to initialize lightgbm_tpu shim prelude");
      Py_XDECREF(s);
      Py_XDECREF(t);
      Py_XDECREF(v);
      Py_XDECREF(tb);
      rc = -1;
    } else {
      Py_DECREF(res);
      Py_INCREF(dict);
      g_helpers = dict;
    }
    Py_DECREF(mod);
  }
  PyGILState_Release(gil);
  if (owns_interp) {
    // release the GIL the embedded init left held so any thread can
    // PyGILState_Ensure later; do this exactly once
    static bool released = false;
    if (!released) {
      released = true;
      PyEval_SaveThread();
    }
  }
  return rc;
}

// Relay returning `code` and writing up to two int64 outputs.
int relay(const char* helper, PyObject* args, int64_t* out1,
          int64_t* out2) {
  if (ensure_python() != 0) {
    Py_XDECREF(args);
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int code = -1;
  PyObject* res = call_helper(helper, args);
  Py_XDECREF(args);
  if (res != nullptr && PyTuple_Check(res) && PyTuple_Size(res) >= 1) {
    code = (int)PyLong_AsLong(PyTuple_GetItem(res, 0));
    if (code == 0) {
      if (out1 != nullptr && PyTuple_Size(res) >= 2)
        *out1 = PyLong_AsLongLong(PyTuple_GetItem(res, 1));
      if (out2 != nullptr && PyTuple_Size(res) >= 3)
        *out2 = PyLong_AsLongLong(PyTuple_GetItem(res, 2));
    } else {
      fetch_py_error();
    }
  }
  Py_XDECREF(res);
  PyGILState_Release(gil);
  return code;
}

PyObject* build_args(const char* fmt, ...) {
  // must hold no GIL assumptions: ensure_python() first, then GIL
  va_list ap;
  va_start(ap, fmt);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* args = Py_VaBuildValue(fmt, ap);
  PyGILState_Release(gil);
  va_end(ap);
  return args;
}

}  // namespace

extern "C" {

typedef void* DatasetHandle;
typedef void* BoosterHandle;

const char* LGBM_GetLastError() { return g_last_error.c_str(); }

int LGBM_DatasetCreateFromMat(const void* data, int data_type,
                              int32_t nrow, int32_t ncol,
                              int is_row_major, const char* parameters,
                              DatasetHandle reference,
                              DatasetHandle* out) {
  if (ensure_python() != 0) return -1;
  PyObject* args = build_args(
      "(LiiiisL)", (long long)(intptr_t)data, data_type, (int)nrow,
      (int)ncol, is_row_major, parameters ? parameters : "",
      (long long)(intptr_t)reference);
  int64_t h = 0;
  int code = relay("dataset_from_mat", args, &h, nullptr);
  if (code == 0 && out != nullptr) *out = (DatasetHandle)(intptr_t)h;
  return code;
}

int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int num_element,
                         int type) {
  if (ensure_python() != 0) return -1;
  PyObject* args = build_args(
      "(LsLii)", (long long)(intptr_t)handle, field_name,
      (long long)(intptr_t)field_data, num_element, type);
  return relay("dataset_set_field", args, nullptr, nullptr);
}

int LGBM_DatasetFree(DatasetHandle handle) {
  if (ensure_python() != 0) return -1;
  PyObject* args = build_args("(L)", (long long)(intptr_t)handle);
  return relay("dataset_free", args, nullptr, nullptr);
}

int LGBM_BoosterCreate(DatasetHandle train_data, const char* parameters,
                       BoosterHandle* out) {
  if (ensure_python() != 0) return -1;
  PyObject* args = build_args("(Ls)", (long long)(intptr_t)train_data,
                              parameters ? parameters : "");
  int64_t h = 0;
  int code = relay("booster_create", args, &h, nullptr);
  if (code == 0 && out != nullptr) *out = (BoosterHandle)(intptr_t)h;
  return code;
}

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  if (ensure_python() != 0) return -1;
  PyObject* args = build_args("(s)", filename ? filename : "");
  int64_t h = 0, it = 0;
  int code = relay("booster_from_modelfile", args, &h, &it);
  if (code == 0) {
    if (out != nullptr) *out = (BoosterHandle)(intptr_t)h;
    if (out_num_iterations != nullptr) *out_num_iterations = (int)it;
  }
  return code;
}

int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished) {
  if (ensure_python() != 0) return -1;
  PyObject* args = build_args("(L)", (long long)(intptr_t)handle);
  int64_t fin = 0;
  int code = relay("booster_update", args, &fin, nullptr);
  if (code == 0 && is_finished != nullptr) *is_finished = (int)fin;
  return code;
}

int LGBM_BoosterSaveModel(BoosterHandle handle, int start_iteration,
                          int num_iteration,
                          int feature_importance_type,
                          const char* filename) {
  (void)feature_importance_type;
  if (ensure_python() != 0) return -1;
  PyObject* args = build_args(
      "(Liis)", (long long)(intptr_t)handle, start_iteration,
      num_iteration, filename ? filename : "");
  return relay("booster_save", args, nullptr, nullptr);
}

int LGBM_BoosterFree(BoosterHandle handle) {
  if (ensure_python() != 0) return -1;
  PyObject* args = build_args("(L)", (long long)(intptr_t)handle);
  return relay("booster_free", args, nullptr, nullptr);
}

int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result) {
  (void)parameter;
  if (ensure_python() != 0) return -1;
  PyObject* args = build_args(
      "(LLiiiiiiiL)", (long long)(intptr_t)handle,
      (long long)(intptr_t)data, data_type, (int)nrow, (int)ncol,
      is_row_major, predict_type, start_iteration, num_iteration,
      (long long)(intptr_t)out_result);
  int64_t n = 0;
  int code = relay("booster_predict_into", args, &n, nullptr);
  if (code == 0 && out_len != nullptr) *out_len = n;
  return code;
}

}  // extern "C"
