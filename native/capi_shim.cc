// Native LGBM_* ABI shim: the FULL 74-symbol extern "C" surface of the
// reference (include/LightGBM/c_api.h), with the reference's out-pointer
// calling convention, backed by this framework's in-process Python
// surface (lightgbm_tpu/capi.py) through an embedded CPython interpreter.
//
// Design: every exported function is a thin relay — scalars, strings and
// RAW POINTER ADDRESSES cross into a Python helper prelude (defined
// below) which wraps the addresses with ctypes+numpy, calls
// lightgbm_tpu.capi, and writes results back through the caller's out
// pointers.  Handles are the Python registry's integer ids cast to
// void*.  The -1 + LGBM_GetLastError error contract is preserved
// (exceptions are swallowed inside the helper _wrap, so the in-process
// Python capi's raise-by-default mode is untouched).
//
// Lifecycle: if a Python interpreter already exists in the process (the
// common embedding test: ctypes.CDLL from Python), it is reused via
// PyGILState; otherwise one is initialized and its GIL released so any
// thread may call in.
//
// Build: utils/native.py build_capi_shim() —
//   g++ -O2 -shared -fPIC capi_shim.cc $(python3-config --includes
//   --ldflags --embed) -o liblightgbm_tpu_capi.so

#include <Python.h>

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>

namespace {

std::mutex g_mu;
PyObject* g_helpers = nullptr;  // module dict holding the prelude
// thread-local like the reference's last-error storage, so concurrent
// callers never race on the message buffer
thread_local std::string g_last_error = "ok";

const char* safe_utf8(PyObject* s, const char* fallback) {
  const char* c = s ? PyUnicode_AsUTF8(s) : nullptr;
  if (c == nullptr) {
    PyErr_Clear();
    return fallback;
  }
  return c;
}

const char kPrelude[] = R"PY(
import ctypes
import numpy as np
import lightgbm_tpu.capi as capi

_DT = {0: np.float32, 1: np.float64, 2: np.int32, 3: np.int64}
# keep-alive store for arrays whose raw pointers were handed to C
# (DatasetGetField, PredictSparseOutput) — freed by the matching Free call
_keep = {}


def _wrap(fn):
    """-1 codes for the C surface: exceptions are caught HERE, so the
    in-process Python capi keeps its raise-by-default mode untouched
    (no global flag flip; safe under concurrent in-process users)."""
    def inner(*args):
        try:
            return fn(*args)
        except Exception as e:  # noqa: BLE001 — the ABI swallows into -1
            capi._last_error[0] = f"{type(e).__name__}: {e}"
            return (-1,)
    return inner


def _mat(addr, data_type, nrow, ncol, is_row_major):
    n = int(nrow) * int(ncol)
    dt = _DT[int(data_type)]
    buf = (ctypes.c_char * (n * np.dtype(dt).itemsize)).from_address(addr)
    a = np.frombuffer(buf, dtype=dt, count=n)
    return a.reshape((nrow, ncol)) if is_row_major else \
        a.reshape((ncol, nrow)).T


def _vec(addr, data_type, n):
    dt = _DT[int(data_type)]
    buf = (ctypes.c_char * (int(n) * np.dtype(dt).itemsize)).from_address(
        addr)
    return np.frombuffer(buf, dtype=dt, count=int(n))


def _csr(indptr, indptr_type, indices, data, data_type, nindptr, nelem,
         num_col):
    import scipy.sparse as sp
    ip = np.array(_vec(indptr, 2 if indptr_type == 0 else 3, nindptr),
                  np.int64)
    ix = np.array(_vec(indices, 2, nelem), np.int32)
    dv = np.array(_vec(data, data_type, nelem), np.float64)
    return sp.csr_matrix((dv, ix, ip),
                         shape=(int(nindptr) - 1, int(num_col)))


def _csc(col_ptr, col_ptr_type, indices, data, data_type, ncol_ptr, nelem,
         num_row):
    import scipy.sparse as sp
    cp = np.array(_vec(col_ptr, 2 if col_ptr_type == 0 else 3, ncol_ptr),
                  np.int64)
    ix = np.array(_vec(indices, 2, nelem), np.int32)
    dv = np.array(_vec(data, data_type, nelem), np.float64)
    return sp.csc_matrix((dv, ix, cp),
                         shape=(int(num_row), int(ncol_ptr) - 1))


def _out_f64(addr, arr):
    arr = np.atleast_1d(np.asarray(arr, np.float64)).ravel()
    np.copyto(_vec(addr, 1, len(arr)), arr)
    return len(arr)


def _err():
    return capi.LGBM_GetLastError()


# ---- dataset helpers ----

def dataset_from_mat(addr, data_type, nrow, ncol, is_row_major, params,
                     ref):
    X = np.array(_mat(addr, data_type, nrow, ncol, is_row_major),
                 np.float64)
    code, h = capi.LGBM_DatasetCreateFromMat(
        X, params, reference=(ref or None))
    return code, (h or 0)


def dataset_from_mats(addrs_addr, nmat, data_type, nrows_addr, ncol,
                      is_row_major, params, ref):
    addrs = np.array(_vec(addrs_addr, 3, nmat), np.int64)
    nrows = np.array(_vec(nrows_addr, 2, nmat), np.int32)
    mats = [np.array(_mat(int(a), data_type, int(nr), ncol, is_row_major),
                     np.float64) for a, nr in zip(addrs, nrows)]
    code, h = capi.LGBM_DatasetCreateFromMats(
        mats, params, reference=(ref or None))
    return code, (h or 0)


def dataset_from_csr(indptr, indptr_type, indices, data, data_type,
                     nindptr, nelem, num_col, params, ref):
    m = _csr(indptr, indptr_type, indices, data, data_type, nindptr,
             nelem, num_col)
    code, h = capi.LGBM_DatasetCreateFromCSR(m, params,
                                             reference=(ref or None))
    return code, (h or 0)


def dataset_from_csc(col_ptr, col_ptr_type, indices, data, data_type,
                     ncol_ptr, nelem, num_row, params, ref):
    m = _csc(col_ptr, col_ptr_type, indices, data, data_type, ncol_ptr,
             nelem, num_row)
    code, h = capi.LGBM_DatasetCreateFromCSC(m, params,
                                             reference=(ref or None))
    return code, (h or 0)


def dataset_from_file(filename, params, ref):
    code, h = capi.LGBM_DatasetCreateFromFile(filename, params,
                                              reference=(ref or None))
    return code, (h or 0)


def dataset_from_sampled(sample_addr, indices_addr, ncol, num_per_col_addr,
                         num_sample_row, num_total_row, params):
    col_addrs = np.array(_vec(sample_addr, 3, ncol), np.int64)
    idx_addrs = np.array(_vec(indices_addr, 3, ncol), np.int64)
    counts = np.array(_vec(num_per_col_addr, 2, ncol), np.int32)
    cols = [np.array(_vec(int(a), 1, int(c))) for a, c in
            zip(col_addrs, counts)]
    idxs = [np.array(_vec(int(a), 2, int(c))) for a, c in
            zip(idx_addrs, counts)]
    code, h = capi.LGBM_DatasetCreateFromSampledColumn(
        cols, idxs, num_total_row, params, num_sample_row=num_sample_row)
    return code, (h or 0)


def dataset_by_reference(ref, num_total_row):
    code, h = capi.LGBM_DatasetCreateByReference(ref, num_total_row)
    return code, (h or 0)


def dataset_push_rows(handle, addr, data_type, nrow, ncol, start_row):
    X = np.array(_mat(addr, data_type, nrow, ncol, 1), np.float64)
    code, _ = capi.LGBM_DatasetPushRows(handle, X, start_row)
    return code, 0


def dataset_push_rows_csr(handle, indptr, indptr_type, indices, data,
                          data_type, nindptr, nelem, num_col, start_row):
    m = _csr(indptr, indptr_type, indices, data, data_type, nindptr,
             nelem, num_col)
    code, _ = capi.LGBM_DatasetPushRowsByCSR(handle, m, start_row)
    return code, 0


def dataset_get_subset(handle, idx_addr, n_idx, params):
    idx = np.array(_vec(idx_addr, 2, n_idx), np.int64)
    code, h = capi.LGBM_DatasetGetSubset(handle, idx, params)
    return code, (h or 0)


def dataset_set_feature_names(handle, joined):
    code, _ = capi.LGBM_DatasetSetFeatureNames(handle, joined.split("\t"))
    return code, 0


def dataset_get_feature_names(handle):
    code, names = capi.LGBM_DatasetGetFeatureNames(handle)
    return code, "\t".join(names)


def dataset_set_field(handle, name, addr, num_element, data_type):
    v = np.array(_vec(addr, data_type, num_element))
    code, _ = capi.LGBM_DatasetSetField(handle, name, v)
    return code, 0


def dataset_get_field(handle, name):
    code, v = capi.LGBM_DatasetGetField(handle, name)
    if v is None:
        return 0, 0, 0, 0
    if name in ("group", "query"):
        arr = np.ascontiguousarray(v, np.int32)
        dtype = 2
    else:
        arr = np.ascontiguousarray(v, np.float32)
        dtype = 0
    # APPEND (never replace): the reference keeps every pointer handed
    # out valid until DatasetFree, including older results of repeated
    # GetField calls for the same field
    _keep.setdefault(("field", handle, name), []).append(arr)
    return code, len(arr), arr.ctypes.data, dtype


def dataset_free(handle):
    _keep_keys = [k for k in _keep if k[0] == "field" and k[1] == handle]
    for k in _keep_keys:
        del _keep[k]
    code, _ = capi.LGBM_DatasetFree(handle)
    return code, 0


def dataset_save_binary(handle, filename):
    code, _ = capi.LGBM_DatasetSaveBinary(handle, filename)
    return code, 0


def dataset_dump_text(handle, filename):
    code, _ = capi.LGBM_DatasetDumpText(handle, filename)
    return code, 0


def dataset_update_param_checking(old, new):
    code, _ = capi.LGBM_DatasetUpdateParamChecking(old, new)
    return code, 0


def dataset_num_data(handle):
    return capi.LGBM_DatasetGetNumData(handle)


def dataset_num_feature(handle):
    return capi.LGBM_DatasetGetNumFeature(handle)


def dataset_add_features_from(target, source):
    code, _ = capi.LGBM_DatasetAddFeaturesFrom(target, source)
    return code, 0


# ---- booster helpers ----

def booster_create(train_handle, params):
    code, h = capi.LGBM_BoosterCreate(train_handle, params)
    return code, (h or 0)


def booster_from_modelfile(filename):
    code, h = capi.LGBM_BoosterCreateFromModelfile(filename)
    if code != 0:
        return code, 0, 0
    code2, it = capi.LGBM_BoosterGetCurrentIteration(h)
    return code, (h or 0), (it or 0)


def booster_from_string(model_str):
    code, h = capi.LGBM_BoosterLoadModelFromString(model_str)
    if code != 0:
        return code, 0, 0
    code2, it = capi.LGBM_BoosterGetCurrentIteration(h)
    return code, (h or 0), (it or 0)


def booster_free(handle):
    code, _ = capi.LGBM_BoosterFree(handle)
    return code, 0


def booster_shuffle_models(handle, s, e):
    code, _ = capi.LGBM_BoosterShuffleModels(handle, s, e)
    return code, 0


def booster_merge(handle, other):
    code, _ = capi.LGBM_BoosterMerge(handle, other)
    return code, 0


def booster_add_valid(handle, valid):
    code, _ = capi.LGBM_BoosterAddValidData(handle, valid)
    return code, 0


def booster_reset_training_data(handle, train):
    code, _ = capi.LGBM_BoosterResetTrainingData(handle, train)
    return code, 0


def booster_reset_parameter(handle, params):
    code, _ = capi.LGBM_BoosterResetParameter(handle, params)
    return code, 0


def booster_update(handle):
    code, fin = capi.LGBM_BoosterUpdateOneIter(handle)
    return code, int(bool(fin))


def booster_update_custom(handle, grad_addr, hess_addr):
    bst = capi._get(handle)
    g = bst._gbdt
    n = g.num_data * max(g.num_tree_per_iteration, 1)
    grad = np.array(_vec(grad_addr, 0, n), np.float32)
    hess = np.array(_vec(hess_addr, 0, n), np.float32)
    code, fin = capi.LGBM_BoosterUpdateOneIterCustom(handle, grad, hess)
    return code, int(bool(fin))


def booster_rollback(handle):
    code, _ = capi.LGBM_BoosterRollbackOneIter(handle)
    return code, 0


def booster_refit(handle, leaf_addr, nrow, ncol):
    bst = capi._get(handle)
    lp = np.array(_mat(leaf_addr, 2, nrow, ncol, 1), np.int32)
    bst._gbdt.refit_trees(bst._gbdt, lp)
    return 0, 0


def booster_int_prop(handle, which):
    fn = {
        "cur_iter": capi.LGBM_BoosterGetCurrentIteration,
        "models_per_iter": capi.LGBM_BoosterNumModelPerIteration,
        "total_models": capi.LGBM_BoosterNumberOfTotalModel,
        "num_classes": capi.LGBM_BoosterGetNumClasses,
        "num_feature": capi.LGBM_BoosterGetNumFeature,
        "eval_counts": capi.LGBM_BoosterGetEvalCounts,
        "linear": capi.LGBM_BoosterGetLinear,
    }[which]
    code, v = fn(handle)
    return code, int(v)


def booster_eval_names(handle):
    code, names = capi.LGBM_BoosterGetEvalNames(handle)
    return code, "\t".join(names)


def booster_feature_names(handle):
    code, names = capi.LGBM_BoosterGetFeatureNames(handle)
    return code, "\t".join(names)


def booster_get_eval(handle, data_idx, out_addr):
    code, pairs = capi.LGBM_BoosterGetEval(handle, data_idx)
    vals = np.asarray([v for _, v in pairs], np.float64)
    return code, _out_f64(out_addr, vals) if len(vals) else 0


def booster_get_num_predict(handle, data_idx):
    return capi.LGBM_BoosterGetNumPredict(handle, data_idx)


def booster_get_predict(handle, data_idx, out_addr):
    code, out = capi.LGBM_BoosterGetPredict(handle, data_idx)
    return code, _out_f64(out_addr, out)


def booster_predict_for_file(handle, data_filename, has_header,
                             predict_type, start_iteration, num_iteration,
                             parameter, result_filename):
    code, _ = capi.LGBM_BoosterPredictForFile(
        handle, data_filename, bool(has_header), predict_type,
        start_iteration, num_iteration, parameter, result_filename)
    return code, 0


def booster_calc_num_predict(handle, num_row, predict_type,
                             start_iteration, num_iteration):
    return capi.LGBM_BoosterCalcNumPredict(
        handle, num_row, predict_type, start_iteration, num_iteration)


def booster_predict_mat_into(handle, addr, data_type, nrow, ncol,
                             is_row_major, predict_type, start_iteration,
                             num_iteration, out_addr):
    X = np.array(_mat(addr, data_type, nrow, ncol, is_row_major),
                 np.float64)
    code, out = capi.LGBM_BoosterPredictForMat(
        handle, X, predict_type, start_iteration, num_iteration)
    return code, _out_f64(out_addr, out)


def booster_predict_mats_into(handle, addrs_addr, nmat, data_type, ncol,
                              predict_type, start_iteration,
                              num_iteration, out_addr):
    addrs = np.array(_vec(addrs_addr, 3, nmat), np.int64)
    mats = [np.array(_vec(int(a), data_type, ncol), np.float64)
            for a in addrs]
    code, out = capi.LGBM_BoosterPredictForMats(
        handle, mats, predict_type, start_iteration, num_iteration)
    return code, _out_f64(out_addr, out)


def booster_predict_csr_into(handle, indptr, indptr_type, indices, data,
                             data_type, nindptr, nelem, num_col,
                             predict_type, start_iteration, num_iteration,
                             out_addr):
    m = _csr(indptr, indptr_type, indices, data, data_type, nindptr,
             nelem, num_col)
    code, out = capi.LGBM_BoosterPredictForCSR(
        handle, m, predict_type, start_iteration, num_iteration)
    return code, _out_f64(out_addr, out)


def booster_predict_csc_into(handle, col_ptr, col_ptr_type, indices, data,
                             data_type, ncol_ptr, nelem, num_row,
                             predict_type, start_iteration, num_iteration,
                             out_addr):
    m = _csc(col_ptr, col_ptr_type, indices, data, data_type, ncol_ptr,
             nelem, num_row)
    code, out = capi.LGBM_BoosterPredictForCSC(
        handle, m, predict_type, start_iteration, num_iteration)
    return code, _out_f64(out_addr, out)


def booster_predict_single_into(handle, addr, data_type, ncol,
                                is_row_major, predict_type,
                                start_iteration, num_iteration, out_addr):
    row = np.array(_vec(addr, data_type, ncol), np.float64)
    code, out = capi.LGBM_BoosterPredictForMatSingleRow(
        handle, row, predict_type, start_iteration, num_iteration)
    return code, _out_f64(out_addr, out)


def booster_predict_csr_single_into(handle, indptr, indptr_type, indices,
                                    data, data_type, nindptr, nelem,
                                    num_col, predict_type,
                                    start_iteration, num_iteration,
                                    out_addr):
    m = _csr(indptr, indptr_type, indices, data, data_type, nindptr,
             nelem, num_col)
    code, out = capi.LGBM_BoosterPredictForCSRSingleRow(
        handle, m, predict_type, start_iteration, num_iteration)
    return code, _out_f64(out_addr, out)


def fast_init_mat(handle, predict_type, start_iteration, num_iteration,
                  data_type, ncol, parameter):
    code, h = capi.LGBM_BoosterPredictForMatSingleRowFastInit(
        handle, predict_type, start_iteration, num_iteration, data_type,
        ncol, parameter)
    return code, (h or 0)


def fast_init_csr(handle, predict_type, start_iteration, num_iteration,
                  data_type, num_col, parameter):
    code, h = capi.LGBM_BoosterPredictForCSRSingleRowFastInit(
        handle, predict_type, start_iteration, num_iteration, data_type,
        num_col, parameter)
    return code, (h or 0)


def fast_predict_mat(fast_handle, addr, out_addr):
    fc = capi._get(fast_handle)
    row = np.array(_vec(addr, fc.dtype, fc.ncol), np.float64)
    code, out = capi.LGBM_BoosterPredictForMatSingleRowFast(
        fast_handle, row)
    return code, _out_f64(out_addr, out)


def fast_predict_csr(fast_handle, indptr, indptr_type, indices, data,
                     nindptr, nelem, out_addr):
    fc = capi._get(fast_handle)
    m = _csr(indptr, indptr_type, indices, data, fc.dtype, nindptr, nelem,
             fc.ncol)
    code, out = capi.LGBM_BoosterPredictForCSRSingleRowFast(fast_handle, m)
    return code, _out_f64(out_addr, out)


def fast_config_free(fast_handle):
    code, _ = capi.LGBM_FastConfigFree(fast_handle)
    return code, 0


def booster_predict_sparse(handle, indptr, indptr_type, indices, data,
                           data_type, nindptr, nelem, num_col_or_row,
                           predict_type, start_iteration, num_iteration,
                           matrix_type, out_indptr, out_indices, out_data):
    """Two-phase sparse output: compute, stash, report sizes; C allocates
    and calls booster_predict_sparse_fill to copy."""
    m = _csr(indptr, indptr_type, indices, data, data_type, nindptr,
             nelem, num_col_or_row)
    code, sm = capi.LGBM_BoosterPredictSparseOutput(
        handle, m, predict_type, start_iteration, num_iteration,
        matrix_type)
    if code != 0:
        return code, 0, 0
    key = ("sparse", id(sm))
    _keep[key] = sm
    return 0, id(sm), len(sm.indptr), sm.nnz


def booster_predict_sparse_fill(key_id, indptr_addr, indices_addr,
                                data_addr, indptr_type):
    sm = _keep.pop(("sparse", key_id))
    ipt = 2 if indptr_type == 0 else 3
    np.copyto(_vec(indptr_addr, ipt, len(sm.indptr)),
              sm.indptr.astype(_DT[ipt]))
    np.copyto(_vec(indices_addr, 2, sm.nnz), sm.indices.astype(np.int32))
    np.copyto(_vec(data_addr, 1, sm.nnz), sm.data.astype(np.float64))
    return 0, 0


def booster_get_leaf_value(handle, tree_idx, leaf_idx):
    code, v = capi.LGBM_BoosterGetLeafValue(handle, tree_idx, leaf_idx)
    return code, float(v)


def booster_set_leaf_value(handle, tree_idx, leaf_idx, val):
    code, _ = capi.LGBM_BoosterSetLeafValue(handle, tree_idx, leaf_idx,
                                            val)
    return code, 0


def booster_feature_importance(handle, num_iteration, importance_type,
                               out_addr):
    code, out = capi.LGBM_BoosterFeatureImportance(
        handle, num_iteration, importance_type)
    return code, _out_f64(out_addr, out)


def booster_bound(handle, upper):
    fn = capi.LGBM_BoosterGetUpperBoundValue if upper else \
        capi.LGBM_BoosterGetLowerBoundValue
    code, v = fn(handle)
    return code, float(v)


def booster_save(handle, start_iteration, num_iteration,
                 importance_type, filename):
    code, _ = capi.LGBM_BoosterSaveModel(handle, filename,
                                         start_iteration, num_iteration)
    return code, 0


def booster_to_string(handle, start_iteration, num_iteration,
                      importance_type):
    code, s = capi.LGBM_BoosterSaveModelToString(handle, start_iteration,
                                                 num_iteration)
    return code, s


def booster_dump_model(handle, start_iteration, num_iteration,
                       importance_type):
    import json
    code, d = capi.LGBM_BoosterDumpModel(handle, start_iteration,
                                         num_iteration)
    return code, d if isinstance(d, str) else json.dumps(d)


def register_log_callback(addr):
    if not addr:
        capi.LGBM_RegisterLogCallback(None)
        return 0, 0
    cfunc = ctypes.CFUNCTYPE(None, ctypes.c_char_p)(addr)
    capi.LGBM_RegisterLogCallback(
        lambda msg: cfunc(msg.encode("utf-8", "replace")))
    return 0, 0


def network_init(machines, port, timeout, num_machines):
    code, _ = capi.LGBM_NetworkInit(machines, port, timeout, num_machines)
    return code, 0


def network_init_with_functions(num_machines, rank):
    code, _ = capi.LGBM_NetworkInitWithFunctions(num_machines, rank)
    return code, 0


def network_free():
    code, _ = capi.LGBM_NetworkFree()
    return code, 0


for _n in [k for k, v in list(globals().items())
           if callable(v) and not k.startswith("_")]:
    globals()[_n] = _wrap(globals()[_n])
)PY";

// Run one helper and unpack its (code, value...) tuple.  Caller holds
// the GIL.
PyObject* call_helper(const char* name, PyObject* args) {
  PyObject* fn = PyDict_GetItemString(g_helpers, name);  // borrowed
  if (fn == nullptr) {
    g_last_error = std::string("helper missing: ") + name;
    return nullptr;
  }
  PyObject* res = PyObject_CallObject(fn, args);
  if (res == nullptr) {
    PyObject *t, *v, *tb;
    PyErr_Fetch(&t, &v, &tb);
    PyObject* s = v ? PyObject_Str(v) : nullptr;
    g_last_error = safe_utf8(s, "python exception");
    Py_XDECREF(s);
    Py_XDECREF(t);
    Py_XDECREF(v);
    Py_XDECREF(tb);
    return nullptr;
  }
  return res;
}

bool fetch_py_error() {
  // after a swallowed exception the message lives in LGBM_GetLastError
  PyObject* args = PyTuple_New(0);
  PyObject* res = call_helper("_err", args);
  Py_DECREF(args);
  if (res != nullptr) {
    if (PyUnicode_Check(res))
      g_last_error = safe_utf8(res, "unavailable error message");
    Py_DECREF(res);
  }
  return true;
}

int ensure_python() {
  std::lock_guard<std::mutex> lk(g_mu);
  static bool owns_interp = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    owns_interp = true;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 0;
  if (g_helpers == nullptr) {
    PyObject* mod = PyModule_New("lightgbm_tpu_capi_shim");
    PyObject* dict = PyModule_GetDict(mod);
    PyDict_SetItemString(dict, "__builtins__", PyEval_GetBuiltins());
    PyObject* res = PyRun_String(kPrelude, Py_file_input, dict, dict);
    if (res == nullptr) {
      PyObject *t, *v, *tb;
      PyErr_Fetch(&t, &v, &tb);
      PyObject* s = v ? PyObject_Str(v) : nullptr;
      g_last_error = safe_utf8(
          s, "failed to initialize lightgbm_tpu shim prelude");
      Py_XDECREF(s);
      Py_XDECREF(t);
      Py_XDECREF(v);
      Py_XDECREF(tb);
      rc = -1;
    } else {
      Py_DECREF(res);
      Py_INCREF(dict);
      g_helpers = dict;
    }
    Py_DECREF(mod);
  }
  PyGILState_Release(gil);
  if (owns_interp) {
    // release the GIL the embedded init left held so any thread can
    // PyGILState_Ensure later; do this exactly once
    static bool released = false;
    if (!released) {
      released = true;
      PyEval_SaveThread();
    }
  }
  return rc;
}

// Relay returning `code` and writing up to three int64 outputs.
int relay(const char* helper, PyObject* args, int64_t* out1,
          int64_t* out2, int64_t* out3 = nullptr) {
  if (ensure_python() != 0) {
    Py_XDECREF(args);
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int code = -1;
  PyObject* res = call_helper(helper, args);
  Py_XDECREF(args);
  if (res != nullptr && PyTuple_Check(res) && PyTuple_Size(res) >= 1) {
    code = (int)PyLong_AsLong(PyTuple_GetItem(res, 0));
    if (code == 0) {
      if (out1 != nullptr && PyTuple_Size(res) >= 2)
        *out1 = PyLong_AsLongLong(PyTuple_GetItem(res, 1));
      if (out2 != nullptr && PyTuple_Size(res) >= 3)
        *out2 = PyLong_AsLongLong(PyTuple_GetItem(res, 2));
      if (out3 != nullptr && PyTuple_Size(res) >= 4)
        *out3 = PyLong_AsLongLong(PyTuple_GetItem(res, 3));
    } else {
      fetch_py_error();
    }
  }
  Py_XDECREF(res);
  PyGILState_Release(gil);
  return code;
}

// Relay whose second tuple element is a double.
int relay_f64(const char* helper, PyObject* args, double* out) {
  if (ensure_python() != 0) {
    Py_XDECREF(args);
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int code = -1;
  PyObject* res = call_helper(helper, args);
  Py_XDECREF(args);
  if (res != nullptr && PyTuple_Check(res) && PyTuple_Size(res) >= 1) {
    code = (int)PyLong_AsLong(PyTuple_GetItem(res, 0));
    if (code == 0) {
      if (out != nullptr && PyTuple_Size(res) >= 2)
        *out = PyFloat_AsDouble(PyTuple_GetItem(res, 1));
    } else {
      fetch_py_error();
    }
  }
  Py_XDECREF(res);
  PyGILState_Release(gil);
  return code;
}

// Relay whose second tuple element is a string; copied into out_str with
// truncation, the full length reported through out_len.
int relay_str(const char* helper, PyObject* args, char* out_str,
              int64_t buffer_len, int64_t* out_len) {
  if (ensure_python() != 0) {
    Py_XDECREF(args);
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int code = -1;
  PyObject* res = call_helper(helper, args);
  Py_XDECREF(args);
  if (res != nullptr && PyTuple_Check(res) && PyTuple_Size(res) >= 1) {
    code = (int)PyLong_AsLong(PyTuple_GetItem(res, 0));
    if (code != 0 || PyTuple_Size(res) < 2) {
      if (code == 0) code = -1;
      fetch_py_error();
    } else {
      Py_ssize_t n = 0;
      const char* s =
          PyUnicode_AsUTF8AndSize(PyTuple_GetItem(res, 1), &n);
      if (s == nullptr) {
        PyErr_Clear();
        code = -1;
        g_last_error = "non-utf8 result string";
      } else {
        if (out_len != nullptr) *out_len = (int64_t)n + 1;
        if (out_str != nullptr && buffer_len > 0) {
          int64_t c = n + 1 < buffer_len ? n + 1 : buffer_len;
          std::memcpy(out_str, s, (size_t)(c - 1));
          out_str[c - 1] = '\0';
        }
      }
    }
  }
  Py_XDECREF(res);
  PyGILState_Release(gil);
  return code;
}

// Relay whose second element is a '\t'-joined string list, scattered into
// the (len x buffer_len) char* array convention of the reference.
int relay_strlist(const char* helper, PyObject* args, int len,
                  int* out_len, size_t buffer_len, size_t* out_buffer_len,
                  char** out_strs) {
  if (ensure_python() != 0) {
    Py_XDECREF(args);
    return -1;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  int code = -1;
  PyObject* res = call_helper(helper, args);
  Py_XDECREF(args);
  if (res != nullptr && PyTuple_Check(res) && PyTuple_Size(res) >= 1) {
    code = (int)PyLong_AsLong(PyTuple_GetItem(res, 0));
    if (code != 0 || PyTuple_Size(res) < 2) {
      if (code == 0) code = -1;
      fetch_py_error();
    } else {
      const char* joined = safe_utf8(PyTuple_GetItem(res, 1), "");
      // split on '\t'
      size_t max_needed = 1;
      int count = 0;
      const char* p = joined;
      while (*p != '\0' || count == 0) {
        const char* q = std::strchr(p, '\t');
        size_t seg = q ? (size_t)(q - p) : std::strlen(p);
        if (seg + 1 > max_needed) max_needed = seg + 1;
        if (out_strs != nullptr && count < len && buffer_len > 0) {
          size_t c = seg + 1 < buffer_len ? seg + 1 : buffer_len;
          std::memcpy(out_strs[count], p, c - 1);
          out_strs[count][c - 1] = '\0';
        }
        ++count;
        if (q == nullptr) break;
        p = q + 1;
      }
      if (joined[0] == '\0') count = 0;
      if (out_len != nullptr) *out_len = count;
      if (out_buffer_len != nullptr) *out_buffer_len = max_needed;
    }
  }
  Py_XDECREF(res);
  PyGILState_Release(gil);
  return code;
}

PyObject* build_args(const char* fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* args = Py_VaBuildValue(fmt, ap);
  PyGILState_Release(gil);
  va_end(ap);
  return args;
}

#define ADDR(p) ((long long)(intptr_t)(p))

}  // namespace

extern "C" {

typedef void* DatasetHandle;
typedef void* BoosterHandle;
typedef void* FastConfigHandle;

const char* LGBM_GetLastError() { return g_last_error.c_str(); }

// ---- dataset ------------------------------------------------------------

int LGBM_DatasetCreateFromFile(const char* filename, const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("dataset_from_file",
                   build_args("(ssL)", filename ? filename : "",
                              parameters ? parameters : "",
                              ADDR(reference)),
                   &h, nullptr);
  if (code == 0 && out) *out = (DatasetHandle)(intptr_t)h;
  return code;
}

int LGBM_DatasetCreateFromSampledColumn(double** sample_data,
                                        int** sample_indices, int32_t ncol,
                                        const int* num_per_col,
                                        int32_t num_sample_row,
                                        int32_t num_total_row,
                                        const char* parameters,
                                        DatasetHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("dataset_from_sampled",
                   build_args("(LLiLiis)", ADDR(sample_data),
                              ADDR(sample_indices), (int)ncol,
                              ADDR(num_per_col), (int)num_sample_row,
                              (int)num_total_row,
                              parameters ? parameters : ""),
                   &h, nullptr);
  if (code == 0 && out) *out = (DatasetHandle)(intptr_t)h;
  return code;
}

int LGBM_DatasetCreateByReference(const DatasetHandle reference,
                                  int64_t num_total_row,
                                  DatasetHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("dataset_by_reference",
                   build_args("(LL)", ADDR(reference),
                              (long long)num_total_row),
                   &h, nullptr);
  if (code == 0 && out) *out = (DatasetHandle)(intptr_t)h;
  return code;
}

int LGBM_DatasetPushRows(DatasetHandle dataset, const void* data,
                         int data_type, int32_t nrow, int32_t ncol,
                         int32_t start_row) {
  if (ensure_python() != 0) return -1;
  return relay("dataset_push_rows",
               build_args("(LLiiii)", ADDR(dataset), ADDR(data), data_type,
                          (int)nrow, (int)ncol, (int)start_row),
               nullptr, nullptr);
}

int LGBM_DatasetPushRowsByCSR(DatasetHandle dataset, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int64_t start_row) {
  if (ensure_python() != 0) return -1;
  return relay("dataset_push_rows_csr",
               build_args("(LLiLLiLLLL)", ADDR(dataset), ADDR(indptr),
                          indptr_type, ADDR(indices), ADDR(data), data_type,
                          (long long)nindptr, (long long)nelem,
                          (long long)num_col, (long long)start_row),
               nullptr, nullptr);
}

int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t nindptr, int64_t nelem,
                              int64_t num_col, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("dataset_from_csr",
                   build_args("(LiLLiLLLsL)", ADDR(indptr), indptr_type,
                              ADDR(indices), ADDR(data), data_type,
                              (long long)nindptr, (long long)nelem,
                              (long long)num_col,
                              parameters ? parameters : "",
                              ADDR(reference)),
                   &h, nullptr);
  if (code == 0 && out) *out = (DatasetHandle)(intptr_t)h;
  return code;
}

int LGBM_DatasetCreateFromCSRFunc(void* get_row_funptr, int num_rows,
                                  int64_t num_col, const char* parameters,
                                  const DatasetHandle reference,
                                  DatasetHandle* out) {
  (void)get_row_funptr;
  (void)num_rows;
  (void)num_col;
  (void)parameters;
  (void)reference;
  (void)out;
  // the reference consumes a C++ std::function here (not a C-ABI
  // pointer); no stable cross-compiler contract exists to relay it.
  // The in-process surface (lightgbm_tpu.capi.LGBM_DatasetCreateFromCSRFunc)
  // supports callables; native callers should use CreateFromCSR.
  g_last_error =
      "LGBM_DatasetCreateFromCSRFunc takes a C++ std::function in the "
      "reference ABI and cannot cross a C boundary portably; use "
      "LGBM_DatasetCreateFromCSR (or the in-process Python capi)";
  return -1;
}

int LGBM_DatasetCreateFromCSC(const void* col_ptr, int col_ptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t ncol_ptr,
                              int64_t nelem, int64_t num_row,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("dataset_from_csc",
                   build_args("(LiLLiLLLsL)", ADDR(col_ptr), col_ptr_type,
                              ADDR(indices), ADDR(data), data_type,
                              (long long)ncol_ptr, (long long)nelem,
                              (long long)num_row,
                              parameters ? parameters : "",
                              ADDR(reference)),
                   &h, nullptr);
  if (code == 0 && out) *out = (DatasetHandle)(intptr_t)h;
  return code;
}

int LGBM_DatasetCreateFromMat(const void* data, int data_type,
                              int32_t nrow, int32_t ncol,
                              int is_row_major, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("dataset_from_mat",
                   build_args("(LiiiisL)", ADDR(data), data_type, (int)nrow,
                              (int)ncol, is_row_major,
                              parameters ? parameters : "",
                              ADDR(reference)),
                   &h, nullptr);
  if (code == 0 && out) *out = (DatasetHandle)(intptr_t)h;
  return code;
}

int LGBM_DatasetCreateFromMats(int32_t nmat, const void** data,
                               int data_type, int32_t* nrow, int32_t ncol,
                               int is_row_major, const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("dataset_from_mats",
                   build_args("(LiiLiisL)", ADDR(data), (int)nmat,
                              data_type, ADDR(nrow), (int)ncol,
                              is_row_major, parameters ? parameters : "",
                              ADDR(reference)),
                   &h, nullptr);
  if (code == 0 && out) *out = (DatasetHandle)(intptr_t)h;
  return code;
}

int LGBM_DatasetGetSubset(const DatasetHandle handle,
                          const int32_t* used_row_indices,
                          int32_t num_used_row_indices,
                          const char* parameters, DatasetHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("dataset_get_subset",
                   build_args("(LLis)", ADDR(handle),
                              ADDR(used_row_indices),
                              (int)num_used_row_indices,
                              parameters ? parameters : ""),
                   &h, nullptr);
  if (code == 0 && out) *out = (DatasetHandle)(intptr_t)h;
  return code;
}

int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                const char** feature_names,
                                int num_feature_names) {
  if (ensure_python() != 0) return -1;
  std::string joined;
  for (int i = 0; i < num_feature_names; ++i) {
    if (i) joined += '\t';
    joined += feature_names[i] ? feature_names[i] : "";
  }
  return relay("dataset_set_feature_names",
               build_args("(Ls)", ADDR(handle), joined.c_str()),
               nullptr, nullptr);
}

int LGBM_DatasetGetFeatureNames(DatasetHandle handle, const int len,
                                int* num_feature_names,
                                const size_t buffer_len,
                                size_t* out_buffer_len,
                                char** feature_names) {
  if (ensure_python() != 0) return -1;
  return relay_strlist("dataset_get_feature_names",
                       build_args("(L)", ADDR(handle)), len,
                       num_feature_names, buffer_len, out_buffer_len,
                       feature_names);
}

int LGBM_DatasetFree(DatasetHandle handle) {
  if (ensure_python() != 0) return -1;
  return relay("dataset_free", build_args("(L)", ADDR(handle)), nullptr,
               nullptr);
}

int LGBM_DatasetSaveBinary(DatasetHandle handle, const char* filename) {
  if (ensure_python() != 0) return -1;
  return relay("dataset_save_binary",
               build_args("(Ls)", ADDR(handle), filename ? filename : ""),
               nullptr, nullptr);
}

int LGBM_DatasetDumpText(DatasetHandle handle, const char* filename) {
  if (ensure_python() != 0) return -1;
  return relay("dataset_dump_text",
               build_args("(Ls)", ADDR(handle), filename ? filename : ""),
               nullptr, nullptr);
}

int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int num_element,
                         int type) {
  if (ensure_python() != 0) return -1;
  return relay("dataset_set_field",
               build_args("(LsLii)", ADDR(handle), field_name,
                          ADDR(field_data), num_element, type),
               nullptr, nullptr);
}

int LGBM_DatasetGetField(DatasetHandle handle, const char* field_name,
                         int* out_len, const void** out_ptr,
                         int* out_type) {
  if (ensure_python() != 0) return -1;
  int64_t n = 0, addr = 0, dtype = 0;
  int code = relay("dataset_get_field",
                   build_args("(Ls)", ADDR(handle), field_name), &n, &addr,
                   &dtype);
  if (code == 0) {
    if (out_len) *out_len = (int)n;
    if (out_ptr) *out_ptr = (const void*)(intptr_t)addr;
    if (out_type) *out_type = (int)dtype;
  }
  return code;
}

int LGBM_DatasetUpdateParamChecking(const char* old_parameters,
                                    const char* new_parameters) {
  if (ensure_python() != 0) return -1;
  return relay("dataset_update_param_checking",
               build_args("(ss)", old_parameters ? old_parameters : "",
                          new_parameters ? new_parameters : ""),
               nullptr, nullptr);
}

int LGBM_DatasetGetNumData(DatasetHandle handle, int* out) {
  if (ensure_python() != 0) return -1;
  int64_t v = 0;
  int code = relay("dataset_num_data", build_args("(L)", ADDR(handle)), &v,
                   nullptr);
  if (code == 0 && out) *out = (int)v;
  return code;
}

int LGBM_DatasetGetNumFeature(DatasetHandle handle, int* out) {
  if (ensure_python() != 0) return -1;
  int64_t v = 0;
  int code = relay("dataset_num_feature", build_args("(L)", ADDR(handle)),
                   &v, nullptr);
  if (code == 0 && out) *out = (int)v;
  return code;
}

int LGBM_DatasetAddFeaturesFrom(DatasetHandle target,
                                DatasetHandle source) {
  if (ensure_python() != 0) return -1;
  return relay("dataset_add_features_from",
               build_args("(LL)", ADDR(target), ADDR(source)), nullptr,
               nullptr);
}

// ---- booster ------------------------------------------------------------

int LGBM_BoosterGetLinear(BoosterHandle handle, bool* out) {
  if (ensure_python() != 0) return -1;
  int64_t v = 0;
  int code = relay("booster_int_prop",
                   build_args("(Ls)", ADDR(handle), "linear"), &v, nullptr);
  if (code == 0 && out) *out = v != 0;
  return code;
}

int LGBM_BoosterCreate(const DatasetHandle train_data,
                       const char* parameters, BoosterHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("booster_create",
                   build_args("(Ls)", ADDR(train_data),
                              parameters ? parameters : ""),
                   &h, nullptr);
  if (code == 0 && out) *out = (BoosterHandle)(intptr_t)h;
  return code;
}

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0, it = 0;
  int code = relay("booster_from_modelfile",
                   build_args("(s)", filename ? filename : ""), &h, &it);
  if (code == 0) {
    if (out) *out = (BoosterHandle)(intptr_t)h;
    if (out_num_iterations) *out_num_iterations = (int)it;
  }
  return code;
}

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0, it = 0;
  int code = relay("booster_from_string",
                   build_args("(s)", model_str ? model_str : ""), &h, &it);
  if (code == 0) {
    if (out) *out = (BoosterHandle)(intptr_t)h;
    if (out_num_iterations) *out_num_iterations = (int)it;
  }
  return code;
}

int LGBM_BoosterFree(BoosterHandle handle) {
  if (ensure_python() != 0) return -1;
  return relay("booster_free", build_args("(L)", ADDR(handle)), nullptr,
               nullptr);
}

int LGBM_BoosterShuffleModels(BoosterHandle handle, int start_iter,
                              int end_iter) {
  if (ensure_python() != 0) return -1;
  return relay("booster_shuffle_models",
               build_args("(Lii)", ADDR(handle), start_iter, end_iter),
               nullptr, nullptr);
}

int LGBM_BoosterMerge(BoosterHandle handle, BoosterHandle other_handle) {
  if (ensure_python() != 0) return -1;
  return relay("booster_merge",
               build_args("(LL)", ADDR(handle), ADDR(other_handle)),
               nullptr, nullptr);
}

int LGBM_BoosterAddValidData(BoosterHandle handle,
                             const DatasetHandle valid_data) {
  if (ensure_python() != 0) return -1;
  return relay("booster_add_valid",
               build_args("(LL)", ADDR(handle), ADDR(valid_data)), nullptr,
               nullptr);
}

int LGBM_BoosterResetTrainingData(BoosterHandle handle,
                                  const DatasetHandle train_data) {
  if (ensure_python() != 0) return -1;
  return relay("booster_reset_training_data",
               build_args("(LL)", ADDR(handle), ADDR(train_data)), nullptr,
               nullptr);
}

int LGBM_BoosterResetParameter(BoosterHandle handle,
                               const char* parameters) {
  if (ensure_python() != 0) return -1;
  return relay("booster_reset_parameter",
               build_args("(Ls)", ADDR(handle),
                          parameters ? parameters : ""),
               nullptr, nullptr);
}

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len) {
  if (ensure_python() != 0) return -1;
  int64_t v = 0;
  int code = relay("booster_int_prop",
                   build_args("(Ls)", ADDR(handle), "num_classes"), &v,
                   nullptr);
  if (code == 0 && out_len) *out_len = (int)v;
  return code;
}

int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished) {
  if (ensure_python() != 0) return -1;
  int64_t fin = 0;
  int code = relay("booster_update", build_args("(L)", ADDR(handle)), &fin,
                   nullptr);
  if (code == 0 && is_finished) *is_finished = (int)fin;
  return code;
}

int LGBM_BoosterRefit(BoosterHandle handle, const int32_t* leaf_preds,
                      int32_t nrow, int32_t ncol) {
  if (ensure_python() != 0) return -1;
  return relay("booster_refit",
               build_args("(LLii)", ADDR(handle), ADDR(leaf_preds),
                          (int)nrow, (int)ncol),
               nullptr, nullptr);
}

int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle,
                                    const float* grad, const float* hess,
                                    int* is_finished) {
  if (ensure_python() != 0) return -1;
  int64_t fin = 0;
  int code = relay("booster_update_custom",
                   build_args("(LLL)", ADDR(handle), ADDR(grad),
                              ADDR(hess)),
                   &fin, nullptr);
  if (code == 0 && is_finished) *is_finished = (int)fin;
  return code;
}

int LGBM_BoosterRollbackOneIter(BoosterHandle handle) {
  if (ensure_python() != 0) return -1;
  return relay("booster_rollback", build_args("(L)", ADDR(handle)),
               nullptr, nullptr);
}

static int int_prop(BoosterHandle handle, const char* which, int* out) {
  if (ensure_python() != 0) return -1;
  int64_t v = 0;
  int code = relay("booster_int_prop",
                   build_args("(Ls)", ADDR(handle), which), &v, nullptr);
  if (code == 0 && out) *out = (int)v;
  return code;
}

int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                    int* out_iteration) {
  return int_prop(handle, "cur_iter", out_iteration);
}

int LGBM_BoosterNumModelPerIteration(BoosterHandle handle,
                                     int* out_tree_per_iteration) {
  return int_prop(handle, "models_per_iter", out_tree_per_iteration);
}

int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out_models) {
  return int_prop(handle, "total_models", out_models);
}

int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len) {
  return int_prop(handle, "eval_counts", out_len);
}

int LGBM_BoosterGetEvalNames(BoosterHandle handle, const int len,
                             int* out_len, const size_t buffer_len,
                             size_t* out_buffer_len, char** out_strs) {
  if (ensure_python() != 0) return -1;
  return relay_strlist("booster_eval_names",
                       build_args("(L)", ADDR(handle)), len, out_len,
                       buffer_len, out_buffer_len, out_strs);
}

int LGBM_BoosterGetFeatureNames(BoosterHandle handle, const int len,
                                int* out_len, const size_t buffer_len,
                                size_t* out_buffer_len, char** out_strs) {
  if (ensure_python() != 0) return -1;
  return relay_strlist("booster_feature_names",
                       build_args("(L)", ADDR(handle)), len, out_len,
                       buffer_len, out_buffer_len, out_strs);
}

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len) {
  return int_prop(handle, "num_feature", out_len);
}

int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx, int* out_len,
                        double* out_results) {
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("booster_get_eval",
                   build_args("(LiL)", ADDR(handle), data_idx,
                              ADDR(out_results)),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = (int)n;
  return code;
}

int LGBM_BoosterGetNumPredict(BoosterHandle handle, int data_idx,
                              int64_t* out_len) {
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("booster_get_num_predict",
                   build_args("(Li)", ADDR(handle), data_idx), &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_BoosterGetPredict(BoosterHandle handle, int data_idx,
                           int64_t* out_len, double* out_result) {
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("booster_get_predict",
                   build_args("(LiL)", ADDR(handle), data_idx,
                              ADDR(out_result)),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_BoosterPredictForFile(BoosterHandle handle,
                               const char* data_filename,
                               int data_has_header, int predict_type,
                               int start_iteration, int num_iteration,
                               const char* parameter,
                               const char* result_filename) {
  if (ensure_python() != 0) return -1;
  return relay("booster_predict_for_file",
               build_args("(Lsiiiiss)", ADDR(handle),
                          data_filename ? data_filename : "",
                          data_has_header, predict_type, start_iteration,
                          num_iteration, parameter ? parameter : "",
                          result_filename ? result_filename : ""),
               nullptr, nullptr);
}

int LGBM_BoosterCalcNumPredict(BoosterHandle handle, int num_row,
                               int predict_type, int start_iteration,
                               int num_iteration, int64_t* out_len) {
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("booster_calc_num_predict",
                   build_args("(Liiii)", ADDR(handle), num_row,
                              predict_type, start_iteration, num_iteration),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_FastConfigFree(FastConfigHandle fastConfig) {
  if (ensure_python() != 0) return -1;
  return relay("fast_config_free", build_args("(L)", ADDR(fastConfig)),
               nullptr, nullptr);
}

int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result) {
  (void)parameter;
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("booster_predict_csr_into",
                   build_args("(LLiLLiLLLiiiL)", ADDR(handle), ADDR(indptr),
                              indptr_type, ADDR(indices), ADDR(data),
                              data_type, (long long)nindptr,
                              (long long)nelem, (long long)num_col,
                              predict_type, start_iteration, num_iteration,
                              ADDR(out_result)),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_BoosterPredictSparseOutput(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col_or_row,
    int predict_type, int start_iteration, int num_iteration,
    const char* parameter, int matrix_type, int64_t* out_len,
    void** out_indptr, int32_t** out_indices, void** out_data) {
  (void)parameter;
  if (ensure_python() != 0) return -1;
  int64_t key = 0, n_indptr = 0, nnz = 0;
  int code = relay("booster_predict_sparse",
                   build_args("(LLiLLiLLLiiii)", ADDR(handle), ADDR(indptr),
                              indptr_type, ADDR(indices), ADDR(data),
                              data_type, (long long)nindptr,
                              (long long)nelem, (long long)num_col_or_row,
                              predict_type, start_iteration, num_iteration,
                              matrix_type),
                   &key, &n_indptr, &nnz);
  if (code != 0) return code;
  size_t ipsz = indptr_type == 0 ? 4 : 8;
  void* ip = std::malloc((size_t)n_indptr * ipsz);
  int32_t* ix = (int32_t*)std::malloc((size_t)nnz * sizeof(int32_t));
  double* dv = (double*)std::malloc((size_t)nnz * sizeof(double));
  if (ip == nullptr || ix == nullptr || dv == nullptr) {
    std::free(ip);
    std::free(ix);
    std::free(dv);
    g_last_error = "out of memory for sparse predict buffers";
    return -1;
  }
  code = relay("booster_predict_sparse_fill",
               build_args("(LLLLi)", (long long)key, ADDR(ip), ADDR(ix),
                          ADDR(dv), indptr_type),
               nullptr, nullptr);
  if (code != 0) {
    std::free(ip);
    std::free(ix);
    std::free(dv);
    return code;
  }
  // reference contract (c_api.cpp PredictSparseOutput): out_len is an
  // int64[2] — [0] = element count (nnz), [1] = indptr length
  if (out_len) {
    out_len[0] = nnz;
    out_len[1] = n_indptr;
  }
  if (out_indptr) *out_indptr = ip;
  if (out_indices) *out_indices = ix;
  if (out_data) *out_data = dv;
  return 0;
}

int LGBM_BoosterFreePredictSparse(void* indptr, int32_t* indices,
                                  void* data, int indptr_type,
                                  int data_type) {
  (void)indptr_type;
  (void)data_type;
  std::free(indptr);
  std::free(indices);
  std::free(data);
  return 0;
}

int LGBM_BoosterPredictForCSRSingleRow(
    BoosterHandle handle, const void* indptr, int indptr_type,
    const int32_t* indices, const void* data, int data_type,
    int64_t nindptr, int64_t nelem, int64_t num_col, int predict_type,
    int start_iteration, int num_iteration, const char* parameter,
    int64_t* out_len, double* out_result) {
  (void)parameter;
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("booster_predict_csr_single_into",
                   build_args("(LLiLLiLLLiiiL)", ADDR(handle), ADDR(indptr),
                              indptr_type, ADDR(indices), ADDR(data),
                              data_type, (long long)nindptr,
                              (long long)nelem, (long long)num_col,
                              predict_type, start_iteration, num_iteration,
                              ADDR(out_result)),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_BoosterPredictForCSRSingleRowFastInit(
    BoosterHandle handle, const int predict_type, const int start_iteration,
    const int num_iteration, const int data_type, const int64_t num_col,
    const char* parameter, FastConfigHandle* out_fastConfig) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("fast_init_csr",
                   build_args("(LiiiiLs)", ADDR(handle), predict_type,
                              start_iteration, num_iteration, data_type,
                              (long long)num_col,
                              parameter ? parameter : ""),
                   &h, nullptr);
  if (code == 0 && out_fastConfig)
    *out_fastConfig = (FastConfigHandle)(intptr_t)h;
  return code;
}

int LGBM_BoosterPredictForCSRSingleRowFast(
    FastConfigHandle fastConfig_handle, const void* indptr,
    const int indptr_type, const int32_t* indices, const void* data,
    const int64_t nindptr, const int64_t nelem, int64_t* out_len,
    double* out_result) {
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("fast_predict_csr",
                   build_args("(LLiLLLLL)", ADDR(fastConfig_handle),
                              ADDR(indptr), indptr_type, ADDR(indices),
                              ADDR(data), (long long)nindptr,
                              (long long)nelem, ADDR(out_result)),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_BoosterPredictForCSC(BoosterHandle handle, const void* col_ptr,
                              int col_ptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result) {
  (void)parameter;
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("booster_predict_csc_into",
                   build_args("(LLiLLiLLLiiiL)", ADDR(handle), ADDR(col_ptr),
                              col_ptr_type, ADDR(indices), ADDR(data),
                              data_type, (long long)ncol_ptr,
                              (long long)nelem, (long long)num_row,
                              predict_type, start_iteration, num_iteration,
                              ADDR(out_result)),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int start_iteration, int num_iteration,
                              const char* parameter, int64_t* out_len,
                              double* out_result) {
  (void)parameter;
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("booster_predict_mat_into",
                   build_args("(LLiiiiiiiL)", ADDR(handle), ADDR(data),
                              data_type, (int)nrow, (int)ncol, is_row_major,
                              predict_type, start_iteration, num_iteration,
                              ADDR(out_result)),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_BoosterPredictForMatSingleRow(
    BoosterHandle handle, const void* data, int data_type, int ncol,
    int is_row_major, int predict_type, int start_iteration,
    int num_iteration, const char* parameter, int64_t* out_len,
    double* out_result) {
  (void)parameter;
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("booster_predict_single_into",
                   build_args("(LLiiiiiiL)", ADDR(handle), ADDR(data),
                              data_type, ncol, is_row_major, predict_type,
                              start_iteration, num_iteration,
                              ADDR(out_result)),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_BoosterPredictForMatSingleRowFastInit(
    BoosterHandle handle, const int predict_type, const int start_iteration,
    const int num_iteration, const int data_type, const int32_t ncol,
    const char* parameter, FastConfigHandle* out_fastConfig) {
  if (ensure_python() != 0) return -1;
  int64_t h = 0;
  int code = relay("fast_init_mat",
                   build_args("(Liiiiis)", ADDR(handle), predict_type,
                              start_iteration, num_iteration, data_type,
                              (int)ncol, parameter ? parameter : ""),
                   &h, nullptr);
  if (code == 0 && out_fastConfig)
    *out_fastConfig = (FastConfigHandle)(intptr_t)h;
  return code;
}

int LGBM_BoosterPredictForMatSingleRowFast(
    FastConfigHandle fastConfig_handle, const void* data, int64_t* out_len,
    double* out_result) {
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("fast_predict_mat",
                   build_args("(LLL)", ADDR(fastConfig_handle), ADDR(data),
                              ADDR(out_result)),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_BoosterPredictForMats(BoosterHandle handle, const void** data,
                               int data_type, int32_t nrow, int32_t ncol,
                               int predict_type, int start_iteration,
                               int num_iteration, const char* parameter,
                               int64_t* out_len, double* out_result) {
  (void)parameter;
  if (ensure_python() != 0) return -1;
  int64_t n = 0;
  int code = relay("booster_predict_mats_into",
                   build_args("(LLiiiiiiL)", ADDR(handle), ADDR(data),
                              (int)nrow, data_type, (int)ncol, predict_type,
                              start_iteration, num_iteration,
                              ADDR(out_result)),
                   &n, nullptr);
  if (code == 0 && out_len) *out_len = n;
  return code;
}

int LGBM_BoosterSaveModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          const char* filename) {
  if (ensure_python() != 0) return -1;
  return relay("booster_save",
               build_args("(Liiis)", ADDR(handle), start_iteration,
                          num_iteration, feature_importance_type,
                          filename ? filename : ""),
               nullptr, nullptr);
}

int LGBM_BoosterSaveModelToString(BoosterHandle handle, int start_iteration,
                                  int num_iteration,
                                  int feature_importance_type,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str) {
  if (ensure_python() != 0) return -1;
  return relay_str("booster_to_string",
                   build_args("(Liii)", ADDR(handle), start_iteration,
                              num_iteration, feature_importance_type),
                   out_str, buffer_len, out_len);
}

int LGBM_BoosterDumpModel(BoosterHandle handle, int start_iteration,
                          int num_iteration, int feature_importance_type,
                          int64_t buffer_len, int64_t* out_len,
                          char* out_str) {
  if (ensure_python() != 0) return -1;
  return relay_str("booster_dump_model",
                   build_args("(Liii)", ADDR(handle), start_iteration,
                              num_iteration, feature_importance_type),
                   out_str, buffer_len, out_len);
}

int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double* out_val) {
  if (ensure_python() != 0) return -1;
  return relay_f64("booster_get_leaf_value",
                   build_args("(Lii)", ADDR(handle), tree_idx, leaf_idx),
                   out_val);
}

int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double val) {
  if (ensure_python() != 0) return -1;
  return relay("booster_set_leaf_value",
               build_args("(Liid)", ADDR(handle), tree_idx, leaf_idx, val),
               nullptr, nullptr);
}

int LGBM_BoosterFeatureImportance(BoosterHandle handle, int num_iteration,
                                  int importance_type,
                                  double* out_results) {
  if (ensure_python() != 0) return -1;
  return relay("booster_feature_importance",
               build_args("(LiiL)", ADDR(handle), num_iteration,
                          importance_type, ADDR(out_results)),
               nullptr, nullptr);
}

int LGBM_BoosterGetUpperBoundValue(BoosterHandle handle,
                                   double* out_results) {
  if (ensure_python() != 0) return -1;
  return relay_f64("booster_bound",
                   build_args("(Li)", ADDR(handle), 1), out_results);
}

int LGBM_BoosterGetLowerBoundValue(BoosterHandle handle,
                                   double* out_results) {
  if (ensure_python() != 0) return -1;
  return relay_f64("booster_bound",
                   build_args("(Li)", ADDR(handle), 0), out_results);
}

// ---- misc ---------------------------------------------------------------

int LGBM_RegisterLogCallback(void (*callback)(const char*)) {
  if (ensure_python() != 0) return -1;
  return relay("register_log_callback",
               build_args("(L)", ADDR(callback)), nullptr, nullptr);
}

int LGBM_NetworkInit(const char* machines, int local_listen_port,
                     int listen_time_out, int num_machines) {
  if (ensure_python() != 0) return -1;
  return relay("network_init",
               build_args("(siii)", machines ? machines : "",
                          local_listen_port, listen_time_out, num_machines),
               nullptr, nullptr);
}

int LGBM_NetworkFree() {
  if (ensure_python() != 0) return -1;
  return relay("network_free", build_args("()"), nullptr, nullptr);
}

int LGBM_NetworkInitWithFunctions(int num_machines, int rank,
                                  void* reduce_scatter_ext_fun,
                                  void* allgather_ext_fun) {
  (void)reduce_scatter_ext_fun;
  (void)allgather_ext_fun;
  if (ensure_python() != 0) return -1;
  return relay("network_init_with_functions",
               build_args("(ii)", num_machines, rank), nullptr, nullptr);
}

}  // extern "C"
