// Native host-side binning engine (value -> bin quantization).
//
// TPU-native equivalent of the reference's hot ingest loops
// (reference: src/io/bin.cpp ValueToBin dispatch + dense_bin.hpp push
// paths; the reference parallelizes ingest with OpenMP).  The Python
// BinMapper keeps the bin-BOUNDARY search logic; this library does the
// bulk value->bin mapping with std::thread parallelism — numpy's
// searchsorted is single-threaded and dominated Dataset.construct at
// 10.5M rows (~100 s; this path cuts it to seconds).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread binning.cc -o libbinning.so
// Loaded via ctypes (lightgbm_tpu/utils/native.py); numpy fallback when
// unavailable.

#include <cmath>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

inline int search_left(const double* uppers, int nb, double v) {
  // first index i with uppers[i] >= v  (numpy searchsorted side='left')
  int lo = 0, hi = nb;
  while (lo < hi) {
    int mid = (lo + hi) >> 1;
    if (uppers[mid] < v) {
      lo = mid + 1;
    } else {
      hi = mid;
    }
  }
  return lo;
}

void bin_range(const double* vals, int64_t lo, int64_t hi,
               const double* uppers, int nb, int num_bin, int missing_nan,
               uint8_t* out) {
  const int last_real = nb - 1;
  for (int64_t i = lo; i < hi; ++i) {
    double v = vals[i];
    if (std::isnan(v)) {
      if (missing_nan) {
        out[i] = static_cast<uint8_t>(num_bin - 1);
        continue;
      }
      v = 0.0;  // MissingType::NONE/ZERO route NaN through 0.0
    }
    int b = search_left(uppers, nb, v);
    out[i] = static_cast<uint8_t>(b > last_real ? last_real : b);
  }
}

}  // namespace

extern "C" {

// Bin one numerical column: out[i] = bin of vals[i].
//   uppers: ascending bin upper bounds (nb of them; the real-value bins)
//   num_bin: total bins including a trailing NaN bin when missing_nan
void bin_numerical(const double* vals, int64_t n, const double* uppers,
                   int32_t nb, int32_t num_bin, int32_t missing_nan,
                   uint8_t* out, int32_t n_threads) {
  if (n_threads <= 1 || n < (1 << 16)) {
    bin_range(vals, 0, n, uppers, nb, num_bin, missing_nan, out);
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    workers.emplace_back(bin_range, vals, lo, hi, uppers, nb, num_bin,
                         missing_nan, out);
  }
  for (auto& w : workers) w.join();
}

// Bin a whole row-major float64 matrix (n x f) into a row-major uint8
// matrix, one mapper per column.  Boundary arrays are concatenated;
// offsets[j]..offsets[j+1] delimit column j's uppers.
void bin_matrix_f64(const double* X, int64_t n, int32_t f,
                    const double* uppers_flat, const int64_t* offsets,
                    const int32_t* num_bin, const int32_t* missing_nan,
                    uint8_t* out, int32_t n_threads) {
  auto work = [&](int64_t row_lo, int64_t row_hi) {
    for (int64_t i = row_lo; i < row_hi; ++i) {
      const double* row = X + i * f;
      uint8_t* orow = out + i * f;
      for (int32_t j = 0; j < f; ++j) {
        const double* uppers = uppers_flat + offsets[j];
        int nb = static_cast<int>(offsets[j + 1] - offsets[j]);
        double v = row[j];
        int last_real = nb - 1;
        int b;
        if (std::isnan(v)) {
          if (missing_nan[j]) {
            b = num_bin[j] - 1;
            orow[j] = static_cast<uint8_t>(b);
            continue;
          }
          v = 0.0;
        }
        b = search_left(uppers, nb, v);
        if (b > last_real) b = last_real;
        orow[j] = static_cast<uint8_t>(b);
      }
    }
  };
  if (n_threads <= 1 || n < (1 << 14)) {
    work(0, n);
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    workers.emplace_back(work, lo, hi);
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"
