"""Synthetic-load SLO harness: drive the real HTTP serving tier, judge
it from its own telemetry.

Closes the observability loop the SLO engine opens: train a small
model, start the REAL :class:`PredictionServer` (sockets, JSON, micro
batcher, admission control), drive a ladder of synthetic load rungs
through ``serve/loadgen.py`` (open loop at target QPS or closed loop at
the ceiling, request shapes mixed over the SHAPE_BUCKETS ladder), and
render a pass/breach verdict computed SOLELY from ``/metrics`` and
``/slo`` scrapes — the client-side numbers ride along for context but
never decide anything, so the harness proves the telemetry an operator
would actually page on.

Artifacts: an ``slo-report.json`` (verdict + the /slo payload + the
slowest-request exemplars + per-bucket p50/p99/queue/device split) and
a bench-matrix-v1 record (rows_per_sec / qps / p99_ms rows) that
``scripts/bench_regression.py`` diffs across nightly rounds exactly
like iters/s.

    python benchmarks/loadtest.py [--json out.json] \
        [--slo-report slo-report.json]

Env knobs: LOAD_LADDER ("closed" and/or comma QPS list, e.g.
"10,25,closed"), LOAD_DURATION (s/rung), LOAD_WORKERS, LOAD_FEATURES,
LOAD_TREES, LOAD_LEAVES, LOAD_BUCKETS ("4096:0.9,512:0.1" rows:weight
mix), LOAD_ARRIVAL (uniform|poisson), LOAD_TARGET_ROWS_S (pass floor,
default 1e5), LOAD_P99_MS (re-declares the serve/latency_p99 threshold
for this env), LOAD_MAX_QUEUE_ROWS (admission bound; 0 = unbounded).

``--fleet-chaos`` switches to the fleet-resilience rung: a multi-worker
``FleetSupervisor`` serves open-loop loadgen traffic while the chaos
layer's ``serve_crash_after_n`` hard-kills one worker mid-run; the
verdict — worker crashed AND the fleet recovered to full strength AND
the availability SLO is met after the recovery window AND every client
request reached a terminal outcome — is computed solely from the fleet
``/metrics`` + ``/slo`` scrapes (env knobs: FLEET_WORKERS,
FLEET_DURATION, FLEET_QPS, FLEET_CRASH_AFTER, FLEET_RECOVERY_S).

``--refresh`` runs the model-refresh-under-load rung: the same
per-round updates are deployed to a live server as wire deltas
(``POST /models/<name>/delta``, in-envelope dense splices) and as full
hot-swaps (``POST /models`` reload) while open-loop traffic flows; the
verdict requires the delta lane to reach the head round with ZERO dense
recompiles and both lanes to stay 5xx-free, and the per-lane p99 +
recompile counts land in the bench matrix (env knobs: REFRESH_DURATION,
REFRESH_QPS, REFRESH_BASE_ROUNDS, REFRESH_ROUNDS, REFRESH_SHARD).

``--zoo`` runs the multi-tenant model-zoo rung: zipf-distributed
traffic over 16 same-shape tenants is served twice by the real HTTP
server — once through the zoo's batched cross-model stacked dispatch
and once with stacking off (per-model batchers) — and the verdict
requires the stacked lane to deliver >= 2x rows/s OR >= 4x fewer MXU
launches per 1k requests, with every cold load-on-miss counted and its
p99 reported (env knobs: ZOO_MODELS, ZOO_DURATION, ZOO_THREADS,
ZOO_ROWS, ZOO_ZIPF, ZOO_MAX_WAIT_MS).

``--explain`` runs the explanation-serving rung: closed-loop
``POST /explain`` traffic with interleaved ``/predict`` requests on the
same model; the verdict requires a 5xx-free explain response counter,
the ``serve/explain_latency_p99`` SLO met on the /slo scrape, ZERO
dense->walk fallback batches (a silent host-walk regression fails the
rung even if latency survives), and the untouched predict lane to stay
5xx-free (env knobs: EXPLAIN_DURATION, EXPLAIN_THREADS, EXPLAIN_ROWS,
EXPLAIN_FEATURES, EXPLAIN_TREES, EXPLAIN_LEAVES, EXPLAIN_PREDICT_EVERY,
EXPLAIN_P99_MS).

Exit code: 0 on pass, 1 on breach/underrun — CI runs all modes
blocking, next to the chaos step.
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _git_sha():
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=10).stdout.strip() or None
    except Exception:
        return None


def _train_model(trees: int, leaves: int, features: int, tmp: str) -> str:
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.randn(2000, features).astype(np.float32)
    y = (X[:, 0] + 0.3 * rng.randn(2000) > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": leaves, "verbosity": -1}
    bst = lgb.train(p, lgb.Dataset(X, y, params=p), trees)
    path = os.path.join(tmp, "loadtest_model.txt")
    bst.save_model(path)
    return path


def _parse_bucket_mix(spec: str):
    mix = {}
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if ":" in tok:
            rows, w = tok.split(":", 1)
            mix[int(rows)] = float(w)
        else:
            mix[int(tok)] = 1.0
    return mix or {4096: 1.0}


def _bucket_latency(parsed, model: str):
    """Per-bucket p50/p99 + queue/device split from one /metrics parse."""
    from lightgbm_tpu.serve.loadgen import metric_sum
    out = {}
    for lbl, val in parsed.get("lgbm_tpu_serve_request_latency_ms_p99", ()):
        if lbl.get("model") != model:
            continue
        b = lbl.get("bucket", "?")
        out[b] = {
            "p99_ms": val,
            "p50_ms": metric_sum(
                parsed, "lgbm_tpu_serve_request_latency_ms_p50",
                model=model, bucket=b),
            "queue_wait_p50_ms": metric_sum(
                parsed, "lgbm_tpu_serve_queue_wait_ms_p50",
                model=model, bucket=b),
            "device_p50_ms": metric_sum(
                parsed, "lgbm_tpu_serve_device_ms_p50",
                model=model, bucket=b),
            "requests": metric_sum(
                parsed, "lgbm_tpu_serve_request_latency_ms_count",
                model=model, bucket=b),
        }
    return out


def run_loadtest(ladder=("closed",), duration_s: float = 5.0,
                 workers: int = 3, features: int = 4, trees: int = 20,
                 leaves: int = 15, bucket_mix=None, arrival: str = "uniform",
                 target_rows_per_s: float = 1e5,
                 p99_threshold_ms: float = 0.0,
                 max_queue_rows: int = 0,
                 scrape_interval_s: float = 1.0):
    """Run the ladder against a fresh in-process server; return the
    verdict report.  Every pass/breach number is read back from the
    server's own /metrics and /slo endpoints."""
    from lightgbm_tpu.serve.loadgen import (LoadGenerator, LoadSpec,
                                            metric_sum, parse_prometheus,
                                            scrape_json, scrape_metrics)
    from lightgbm_tpu.serve.registry import ModelRegistry
    from lightgbm_tpu.serve.server import PredictionServer
    from lightgbm_tpu.telemetry.slo import set_latency_threshold
    from lightgbm_tpu.utils.backend import default_backend
    from lightgbm_tpu.utils.log import set_verbosity

    backend = default_backend()
    set_verbosity(-1)
    bucket_mix = dict(bucket_mix or {4096: 1.0})
    if p99_threshold_ms and p99_threshold_ms > 0:
        set_latency_threshold("serve/latency_p99", p99_threshold_ms)

    with tempfile.TemporaryDirectory() as tmp:
        model_file = _train_model(trees, leaves, features, tmp)
        registry = ModelRegistry()
        # a fresh engine: the harness judges THIS run's burn, not
        # whatever the process-wide engine sampled before it
        from lightgbm_tpu.telemetry.slo import SloEngine
        srv = PredictionServer(registry, port=0,
                               max_queue_rows=int(max_queue_rows),
                               slo_engine=SloEngine()).start()
        host, port = srv.host, srv.port
        rungs = []
        try:
            for rung in ladder:
                qps = 0.0 if str(rung).strip() == "closed" else float(rung)
                label = "closed" if qps <= 0 else f"qps{qps:g}"
                # one registry name per rung: the latency windows are
                # cumulative per (model, bucket) series, so a shared
                # name would contaminate each rung's p99 with the
                # previous rungs' samples
                model_name = f"loadtest-{label}"
                registry.load(model_name, model_file, warmup=True)
                spec = LoadSpec(duration_s=duration_s, target_qps=qps,
                                workers=workers, features=features,
                                bucket_mix=bucket_mix, arrival=arrival,
                                model=model_name)
                gen = LoadGenerator(host, port, spec)

                # periodic /slo evaluations while the load flows, so the
                # burn windows sample DURING the rung, not just after it
                stop = threading.Event()

                def scraper():
                    while not stop.wait(scrape_interval_s):
                        try:
                            scrape_json(host, port, "/slo")
                        except Exception:
                            pass

                before = parse_prometheus(scrape_metrics(host, port))
                t0 = time.perf_counter()
                sc = threading.Thread(target=scraper, daemon=True)
                sc.start()
                client = gen.run()
                stop.set()
                sc.join(2.0)
                after = parse_prometheus(scrape_metrics(host, port))
                elapsed = time.perf_counter() - t0
                slo_rep = scrape_json(host, port, "/slo")

                def delta(name, **labels):
                    return metric_sum(after, name, **labels) - \
                        metric_sum(before, name, **labels)

                rows_served = delta("lgbm_tpu_serve_rows_total",
                                    model=model_name)
                reqs = delta("lgbm_tpu_serve_requests_total",
                             model=model_name)
                resp_total = delta(
                    "lgbm_tpu_serve_predict_responses_total")
                resp_5xx = sum(
                    delta("lgbm_tpu_serve_predict_responses_total", code=c)
                    for c in ("500", "503", "504"))
                rungs.append({
                    "label": label,
                    "config": {"target_qps": qps, "duration_s": duration_s,
                               "workers": workers, "features": features,
                               "bucket_mix": {str(k): v for k, v in
                                              sorted(bucket_mix.items())},
                               "arrival": arrival, "backend": backend,
                               "max_queue_rows": int(max_queue_rows)},
                    # server-side truth (the verdict inputs).
                    # Availability reads the /predict-only response
                    # counter — the harness's own /slo+/metrics scrape
                    # 200s must not dilute a shed's severity
                    "rows_per_sec": round(rows_served / elapsed, 1),
                    "qps": round(reqs / elapsed, 2),
                    "availability": round(
                        1.0 - (resp_5xx / resp_total if resp_total
                               else 0.0), 6),
                    "shed": delta("lgbm_tpu_requests_shed_total",
                                  model=model_name),
                    "per_bucket": _bucket_latency(after, model_name),
                    "slo": slo_rep,
                    # client-side context (never judged)
                    "client": client.summary(),
                })
        finally:
            srv.shutdown()

    best = max(rungs, key=lambda r: r["rows_per_sec"]) if rungs else None
    slo_ok = all(r["slo"].get("ok", False) for r in rungs)
    rows_ok = best is not None and \
        best["rows_per_sec"] >= float(target_rows_per_s)
    return {
        "schema": "loadtest-slo-report-v1",
        "git_sha": _git_sha(),
        "backend": backend,
        "verdict": "pass" if (slo_ok and rows_ok) else "breach",
        "slo_ok": slo_ok,
        "rows_ok": rows_ok,
        "target_rows_per_s": float(target_rows_per_s),
        "peak_rows_per_sec": best["rows_per_sec"] if best else 0.0,
        "verdict_source": "/metrics + /slo scrapes only",
        "rungs": rungs,
    }


def run_fleet_chaos(workers: int = 2, duration_s: float = 8.0,
                    qps: float = 30.0, crash_after: int = 40,
                    recovery_window_s: float = 10.0,
                    features: int = 4, trees: int = 20,
                    leaves: int = 15, bucket_rows: int = 8,
                    scrape_interval_s: float = 0.5):
    """Fleet chaos-under-load smoke: start a supervised worker fleet,
    arm worker 0 with ``serve_crash_after_n`` (its FIRST incarnation
    hard-kills itself after N /predict requests — the replacement boots
    clean), drive open-loop traffic through the dispatcher, then judge
    recovery exclusively from fleet ``/metrics`` + ``/slo`` scrapes."""
    from lightgbm_tpu.serve.fleet import FleetSupervisor
    from lightgbm_tpu.serve.loadgen import (LoadGenerator, LoadSpec,
                                            metric_sum, parse_prometheus,
                                            scrape_json, scrape_metrics)
    from lightgbm_tpu.utils.backend import default_backend
    from lightgbm_tpu.utils.log import set_verbosity

    backend = default_backend()
    set_verbosity(-1)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    with tempfile.TemporaryDirectory() as tmp:
        model_file = _train_model(trees, leaves, features, tmp)
        fleet = FleetSupervisor(
            [model_file], workers=int(workers),
            worker_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo},
            worker_args={"warmup": "0", "max_wait_ms": "0.5"},
            first_spawn_env={0: {"LGBM_TPU_FAULTS":
                                 f"serve_crash_after_n={crash_after}"}},
            probe_interval_s=0.25, backoff_base_s=0.2,
            backoff_max_s=1.0, breaker_halfopen_s=1.0,
            startup_timeout_s=300.0,
            run_dir=os.path.join(tmp, "fleet"))
        fleet.start()
        host, port = fleet.host, fleet.port
        try:
            spec = LoadSpec(duration_s=duration_s, target_qps=qps,
                            workers=2, features=features,
                            bucket_mix={int(bucket_rows): 1.0}, seed=1,
                            timeout_s=10.0)
            gen = LoadGenerator(host, port, spec)

            stop = threading.Event()

            def scraper():
                # burn windows sample DURING the chaos, not just after
                while not stop.wait(scrape_interval_s):
                    try:
                        scrape_json(host, port, "/slo")
                    except Exception:
                        pass

            sc = threading.Thread(target=scraper, daemon=True)
            sc.start()
            client = gen.run()
            stop.set()
            sc.join(2.0)

            # recovery window: the supervisor restores full strength
            recovered = False
            deadline = time.perf_counter() + recovery_window_s
            while time.perf_counter() < deadline:
                parsed = parse_prometheus(scrape_metrics(host, port))
                if metric_sum(parsed,
                              "lgbm_tpu_fleet_workers_alive") == workers:
                    recovered = True
                    break
                time.sleep(0.25)

            parsed = parse_prometheus(scrape_metrics(host, port))
            slo_rep = scrape_json(host, port, "/slo")
            restarts = metric_sum(parsed, "lgbm_tpu_fleet_restarts_total")
            retries = metric_sum(parsed, "lgbm_tpu_fleet_retries_total")
            quarantined = metric_sum(parsed,
                                     "lgbm_tpu_fleet_workers_quarantined")
            total = metric_sum(parsed,
                               "lgbm_tpu_serve_predict_responses_total")
            bad = sum(metric_sum(parsed,
                                 "lgbm_tpu_serve_predict_responses_total",
                                 code=c)
                      for c in ("500", "502", "503", "504"))
        finally:
            fleet.shutdown()

    availability = 1.0 - (bad / total) if total else 0.0
    # terminality must be FALSIFIABLE: the sent-vs-outcome ledger
    # balances by construction of the generator loop, so the real
    # assertion is the wall clock — a hung request blocks its
    # generator thread past the per-connection socket timeout, so a
    # run whose elapsed time blows duration + timeout + slack had a
    # request with no terminal outcome inside the client's patience
    ledger_ok = (sum(client.by_code.values()) + client.connect_errors
                 == client.requests_sent)
    no_hang = client.elapsed_s <= duration_s + spec.timeout_s + 5.0
    all_terminal = ledger_ok and no_hang
    crashed = restarts >= 1
    slo_ok = bool(slo_rep.get("ok"))
    verdict = "pass" if (crashed and recovered and slo_ok and
                         all_terminal and total > 0) else "breach"
    return {
        "schema": "fleet-chaos-report-v1",
        "git_sha": _git_sha(),
        "backend": backend,
        "verdict": verdict,
        "verdict_source": "fleet /metrics + /slo scrapes only",
        "config": {"workers": int(workers), "duration_s": duration_s,
                   "target_qps": qps, "crash_after": int(crash_after),
                   "recovery_window_s": recovery_window_s,
                   "bucket_rows": int(bucket_rows)},
        "crashed": crashed,
        "recovered": recovered,
        "slo_ok": slo_ok,
        "all_requests_terminal": all_terminal,
        "availability": round(availability, 6),
        "fleet_restarts_total": restarts,
        "fleet_retries_total": retries,
        "fleet_workers_quarantined": quarantined,
        "qps": round(client.achieved_qps, 2),
        "slo": slo_rep,
        "client": client.summary(),
    }


def _post_json(host: str, port: int, path: str, payload: dict,
               timeout: float = 60.0):
    import http.client
    body = json.dumps(payload).encode()
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", path, body, {
            "Content-Type": "application/json",
            "Content-Length": str(len(body))})
        resp = conn.getresponse()
        raw = resp.read()
        try:
            return resp.status, json.loads(raw)
        except ValueError:
            return resp.status, {}
    finally:
        conn.close()


def run_refresh_under_load(duration_s: float = 6.0, qps: float = 40.0,
                           features: int = 6, base_rounds: int = 4,
                           refresh_rounds: int = 4, shard: int = 16,
                           leaves: int = 15, bucket_rows: int = 8,
                           workers: int = 2):
    """Model-refresh-under-load rung: the same per-round updates are
    deployed to a live server two ways — appended as wire deltas
    (``POST /models/<name>/delta``, in-envelope dense splices) and as
    full-model hot-swaps (``POST /models`` reload) — while open-loop
    traffic flows.  Reports deploy-attributable p99 and the recompile
    count per mode; the verdict requires the delta lane to reach the
    head round with ZERO dense recompiles and both lanes to stay 5xx-
    free, proving live refresh is latency-neutral where the old swap
    path pays a re-lower per round."""
    import base64

    import lightgbm_tpu as lgb
    from lightgbm_tpu.models.model_text import model_to_string
    from lightgbm_tpu.publish.delta import DeltaJournal
    from lightgbm_tpu.serve.loadgen import (LoadGenerator, LoadSpec,
                                            metric_sum, parse_prometheus,
                                            scrape_metrics)
    from lightgbm_tpu.serve.registry import ModelRegistry
    from lightgbm_tpu.serve.server import PredictionServer
    from lightgbm_tpu.utils.backend import default_backend
    from lightgbm_tpu.utils.log import set_verbosity

    backend = default_backend()
    set_verbosity(-1)
    total = base_rounds + refresh_rounds

    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.RandomState(0)
        X = rng.randn(2000, features).astype(np.float32)
        y = (X[:, 0] + 0.3 * rng.randn(2000) > 0).astype(np.float64)
        p = {"objective": "binary", "num_leaves": leaves, "verbosity": -1}
        bst = lgb.train(p, lgb.Dataset(X, y, params=p), total)

        # journal: BASE at base_rounds, one delta per later round; the
        # full-swap lane replays the same rounds as folded text files
        j = DeltaJournal(os.path.join(tmp, "journal"))
        j.write_base(model_to_string(bst._gbdt, num_iteration=base_rounds),
                     base_rounds)
        for r in range(base_rounds + 1, total + 1):
            j.append_delta(model_to_string(bst._gbdt, start_iteration=r - 1,
                                           num_iteration=1), r)
        base_path, base_round = j.base_entry()
        records = list(j.records_after(base_round))
        folded = {}
        for r in range(base_rounds + 1, total + 1):
            path = os.path.join(tmp, f"folded_{r}.txt")
            with open(path, "w") as fh:
                fh.write(model_to_string(bst._gbdt, num_iteration=r))
            folded[r] = path

        registry = ModelRegistry()
        from lightgbm_tpu.telemetry.slo import SloEngine
        srv = PredictionServer(registry, port=0, max_wait_ms=0.5,
                               slo_engine=SloEngine()).start()
        host, port = srv.host, srv.port
        lanes = []
        try:
            for mode in ("delta", "full"):
                name = f"refresh-{mode}"
                # force the dense compiler: the rung measures the dense
                # tree-axis splice, which the CPU cost model would
                # otherwise trade away for walk mode on small models
                registry.load(name, base_path, warmup=True,
                              shard=int(shard), compiler="dense")
                pred0 = registry.get(name)
                r0 = pred0.stats.snapshot()["recompiles"]
                spec = LoadSpec(duration_s=duration_s, target_qps=qps,
                                workers=int(workers), features=features,
                                bucket_mix={int(bucket_rows): 1.0},
                                model=name, seed=2)
                gen = LoadGenerator(host, port, spec)
                interval = duration_s / (len(records) + 1)
                applies = []

                def refresher():
                    # one refresh per interval, spread across the rung
                    for i, rec in enumerate(records):
                        time.sleep(interval)
                        rnd = rec.round
                        try:
                            if mode == "delta":
                                b64 = base64.b64encode(
                                    rec.to_bytes()).decode()
                                code, body = _post_json(
                                    host, port, f"/models/{name}/delta",
                                    {"record_b64": b64})
                            else:
                                code, body = _post_json(
                                    host, port, "/models",
                                    {"name": name, "file": folded[rnd],
                                     "shard": int(shard),
                                     "compiler": "dense"})
                            applies.append(
                                {"round": rnd, "status": code,
                                 "mode": body.get("mode", mode)})
                        except Exception as exc:
                            applies.append({"round": rnd, "status": 0,
                                            "mode": f"error:{exc}"})

                before = parse_prometheus(scrape_metrics(host, port))
                t0 = time.perf_counter()
                rt = threading.Thread(target=refresher, daemon=True)
                rt.start()
                client = gen.run()
                rt.join(10.0)
                after = parse_prometheus(scrape_metrics(host, port))
                elapsed = time.perf_counter() - t0

                def delta_m(metric, **labels):
                    return metric_sum(after, metric, **labels) - \
                        metric_sum(before, metric, **labels)

                resp_total = delta_m(
                    "lgbm_tpu_serve_predict_responses_total")
                resp_5xx = sum(
                    delta_m("lgbm_tpu_serve_predict_responses_total",
                            code=c) for c in ("500", "503", "504"))
                per_bucket = _bucket_latency(after, name)
                p99 = max((b["p99_ms"] for b in per_bucket.values()),
                          default=0.0)
                recompiles = registry.get(name).stats.snapshot()[
                    "recompiles"] - r0
                lanes.append({
                    "mode": mode,
                    "config": {"target_qps": qps,
                               "duration_s": duration_s,
                               "base_rounds": base_rounds,
                               "refresh_rounds": refresh_rounds,
                               "shard": int(shard),
                               "bucket_rows": int(bucket_rows),
                               "backend": backend},
                    "qps": round(delta_m(
                        "lgbm_tpu_serve_requests_total",
                        model=name) / elapsed, 2),
                    "availability": round(
                        1.0 - (resp_5xx / resp_total if resp_total
                               else 0.0), 6),
                    "p99_ms": p99,
                    "per_bucket": per_bucket,
                    "recompiles": recompiles,
                    "final_round": registry.round_of(name),
                    "applies": applies,
                    "client": client.summary(),
                })
        finally:
            srv.shutdown()

    by_mode = {l["mode"]: l for l in lanes}
    d = by_mode.get("delta", {})
    delta_ok = (d.get("final_round") == total
                and d.get("recompiles") == 0
                and all(a["status"] == 200 and a["mode"] == "extend"
                        for a in d.get("applies", []))
                and len(d.get("applies", [])) == refresh_rounds)
    avail_ok = all(l["availability"] >= 1.0 for l in lanes)
    swaps_ok = all(a["status"] == 200
                   for a in by_mode.get("full", {}).get("applies", []))
    return {
        "schema": "refresh-under-load-report-v1",
        "git_sha": _git_sha(),
        "backend": backend,
        "verdict": "pass" if (delta_ok and avail_ok and swaps_ok)
                   else "breach",
        "delta_ok": delta_ok,
        "availability_ok": avail_ok,
        "full_swap_ok": swaps_ok,
        "lanes": lanes,
    }


def _zoo_lane(stacking: bool, model_dir: str, names, duration_s: float,
              threads_n: int, rows_per_req: int, features: int,
              zipf_a: float, max_wait_ms: float):
    """One zoo lane: a fresh zoo-mode server over ``model_dir``, every
    tenant cold-loaded on its first touch, then ``duration_s`` of
    zipf-distributed closed-loop traffic.  Returns server-side truth
    (rows/s, device launches, cold-load p99) from /metrics deltas."""
    from lightgbm_tpu.serve.loadgen import (metric_sum, parse_prometheus,
                                            scrape_metrics)
    from lightgbm_tpu.serve.registry import ModelRegistry
    from lightgbm_tpu.serve.server import PredictionServer
    from lightgbm_tpu.serve.zoo import ModelZoo
    from lightgbm_tpu.telemetry.slo import SloEngine

    registry = ModelRegistry()
    zoo = ModelZoo(registry=registry, max_resident=len(names),
                   source_resolver=model_dir, stacking=stacking,
                   batching=True, max_wait_ms=max_wait_ms, warmup=False)
    srv = PredictionServer(registry, port=0, zoo=zoo,
                           slo_engine=SloEngine()).start()
    host, port = srv.host, srv.port
    rng0 = np.random.RandomState(7)
    probe = rng0.randn(rows_per_req, features).tolist()
    try:
        # counters are process-cumulative across lanes: every read below
        # is a delta against this lane's own start
        start = parse_prometheus(scrape_metrics(host, port))
        # first touch of every tenant IS its cold load (counted +
        # timed by zoo_cold_load_ms); also warms the (stack, bucket)
        # programs so the timed window measures steady state
        for name in names:
            code, _ = _post_json(host, port, "/predict",
                                 {"model": name, "rows": probe})
            if code != 200:
                raise RuntimeError(f"prewarm of {name} -> HTTP {code}")
        for name in names:  # second lap: post-stack-formation programs
            _post_json(host, port, "/predict",
                       {"model": name, "rows": probe})

        before = parse_prometheus(scrape_metrics(host, port))
        counts = {"sent": 0, "ok": 0, "errors": {}}
        lock = threading.Lock()
        t0 = time.perf_counter()
        stop_at = t0 + duration_s
        # synchronized burst ticks — the fan-out scoring pattern the
        # stack exists for: every client fires at the same instant, each
        # at its own zipf-sampled tenant, so one arrival wave holds many
        # distinct tenants (per-model serving pays one launch per tenant
        # in the wave; stacked dispatch one launch per wave)
        barrier = threading.Barrier(threads_n)

        def worker(wid):
            rng = np.random.RandomState(100 + wid)
            rows = rng.randn(rows_per_req, features).tolist()
            sent = ok = 0
            errors = {}
            while time.perf_counter() < stop_at:
                try:
                    barrier.wait(timeout=10.0)
                except threading.BrokenBarrierError:
                    break
                i = min(int(rng.zipf(zipf_a)) - 1, len(names) - 1)
                sent += 1
                try:
                    code, _ = _post_json(host, port, "/predict",
                                         {"model": names[i],
                                          "rows": rows})
                except Exception:
                    errors["connect"] = errors.get("connect", 0) + 1
                    continue
                if code == 200:
                    ok += 1
                else:
                    errors[str(code)] = errors.get(str(code), 0) + 1
            barrier.abort()   # release peers parked on the next tick
            with lock:
                counts["sent"] += sent
                counts["ok"] += ok
                for k, v in errors.items():
                    counts["errors"][k] = counts["errors"].get(k, 0) + v

        threads = [threading.Thread(target=worker, args=(w,))
                   for w in range(threads_n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        after = parse_prometheus(scrape_metrics(host, port))

        def delta(metric, **labels):
            return metric_sum(after, metric, **labels) - \
                metric_sum(before, metric, **labels)

        rows_served = delta("lgbm_tpu_serve_rows_total")
        reqs = delta("lgbm_tpu_serve_requests_total")
        fused = delta("lgbm_tpu_zoo_stack_batches_total")
        # in stacked mode serve_batches_total counts per-LANE slices of
        # a fused launch, so device launches = the fused counter; with
        # stacking off every batch is its own launch
        launches = fused if stacking else delta(
            "lgbm_tpu_serve_batches_total")
        return {
            "mode": "stacked" if stacking else "per-model",
            "rows_per_sec": round(rows_served / elapsed, 1),
            "qps": round(reqs / elapsed, 2),
            "requests": int(reqs),
            "launches": int(launches),
            "launches_per_1k_requests": round(
                1000.0 * launches / reqs, 2) if reqs else 0.0,
            "fused_launches": int(fused),
            "cold_loads": int(
                metric_sum(after, "lgbm_tpu_zoo_cold_loads_total") -
                metric_sum(start, "lgbm_tpu_zoo_cold_loads_total")),
            "cold_load_p99_ms": metric_sum(
                after, "lgbm_tpu_zoo_cold_load_ms_p99"),
            "stack_groups": len(zoo.stack_membership()),
            "availability": round(
                counts["ok"] / counts["sent"], 6) if counts["sent"]
                else 0.0,
            "client": counts,
        }
    finally:
        srv.shutdown()
        zoo.close()


def run_zoo_loadtest(models: int = 16, duration_s: float = 5.0,
                     threads_n: int = 24, rows_per_req: int = 4,
                     features: int = 6, trees: int = 20, leaves: int = 15,
                     zipf_a: float = 1.3, max_wait_ms: float = 10.0):
    """Multi-tenant zoo rung: the SAME zipf workload over ``models``
    same-shape tenants, served stacked (batched cross-model dispatch)
    and per-model; pass needs >= 2x rows/s OR >= 4x fewer launches per
    1k requests for the stacked lane, on top of full availability and
    every tenant cold-loading exactly once."""
    from lightgbm_tpu.utils.backend import default_backend
    from lightgbm_tpu.utils.log import set_verbosity

    backend = default_backend()
    set_verbosity(-1)
    names = [f"tenant{i:02d}" for i in range(int(models))]
    with tempfile.TemporaryDirectory() as tmp:
        model_file = _train_model(trees, leaves, features, tmp)
        zoo_dir = os.path.join(tmp, "zoo")
        os.makedirs(zoo_dir)
        with open(model_file) as fh:
            text = fh.read()
        for name in names:
            with open(os.path.join(zoo_dir, f"{name}.txt"), "w") as fh:
                fh.write(text)
        lanes = [
            _zoo_lane(True, zoo_dir, names, duration_s, threads_n,
                      rows_per_req, features, zipf_a, max_wait_ms),
            _zoo_lane(False, zoo_dir, names, duration_s, threads_n,
                      rows_per_req, features, zipf_a, max_wait_ms),
        ]
    stacked, solo = lanes
    rows_ratio = (stacked["rows_per_sec"] / solo["rows_per_sec"]
                  if solo["rows_per_sec"] else 0.0)
    launch_ratio = (solo["launches_per_1k_requests"] /
                    stacked["launches_per_1k_requests"]
                    if stacked["launches_per_1k_requests"] else 0.0)
    speedup_ok = rows_ratio >= 2.0 or launch_ratio >= 4.0
    avail_ok = all(l["availability"] >= 1.0 for l in lanes)
    cold_ok = all(l["cold_loads"] == len(names) for l in lanes)
    fused_ok = stacked["fused_launches"] > 0 and \
        stacked["stack_groups"] >= 1
    return {
        "schema": "zoo-loadtest-report-v1",
        "git_sha": _git_sha(),
        "backend": backend,
        "verdict": "pass" if (speedup_ok and avail_ok and cold_ok and
                              fused_ok) else "breach",
        "speedup_ok": speedup_ok,
        "availability_ok": avail_ok,
        "cold_loads_ok": cold_ok,
        "fused_ok": fused_ok,
        "rows_ratio": round(rows_ratio, 2),
        "launch_ratio": round(launch_ratio, 2),
        "config": {"models": int(models), "duration_s": duration_s,
                   "threads": int(threads_n),
                   "rows_per_request": int(rows_per_req),
                   "features": int(features), "zipf_a": zipf_a,
                   "max_wait_ms": max_wait_ms, "backend": backend},
        "lanes": lanes,
    }


def run_explain_loadtest(duration_s: float = 5.0, threads_n: int = 4,
                         rows_per_req: int = 8, features: int = 6,
                         trees: int = 20, leaves: int = 15,
                         predict_every: int = 4,
                         p99_threshold_ms: float = 0.0,
                         scrape_interval_s: float = 1.0):
    """Explanation-serving rung: closed-loop ``POST /explain`` traffic
    against a fresh server, with interleaved ``/predict`` requests on
    the same model so the run exercises both lanes at once (the explain
    lane has its own batchers and response counter precisely so a phi
    burst cannot dilute predict availability).  The verdict is read
    back from the server's own telemetry: the explain response counter
    must be 5xx-free, ``serve/explain_latency_p99`` must be met on the
    /slo scrape, the dense compiler must actually have served (ZERO
    fallback batches — a silent walk-path regression flips this), and
    enough requests must land for the SLO window to be falsifiable.
    Client-side additivity (sum(phi) vs served raw scores) rides along
    as context, never as the verdict."""
    from lightgbm_tpu.serve.loadgen import (metric_sum, parse_prometheus,
                                            scrape_json, scrape_metrics)
    from lightgbm_tpu.serve.registry import ModelRegistry
    from lightgbm_tpu.serve.server import PredictionServer
    from lightgbm_tpu.telemetry.slo import SloEngine, set_latency_threshold
    from lightgbm_tpu.utils.backend import default_backend
    from lightgbm_tpu.utils.log import set_verbosity

    backend = default_backend()
    set_verbosity(-1)
    if p99_threshold_ms and p99_threshold_ms > 0:
        set_latency_threshold("serve/explain_latency_p99", p99_threshold_ms)

    model_name = "explain-rung"
    with tempfile.TemporaryDirectory() as tmp:
        model_file = _train_model(trees, leaves, features, tmp)
        registry = ModelRegistry()
        srv = PredictionServer(registry, port=0,
                               slo_engine=SloEngine()).start()
        host, port = srv.host, srv.port
        try:
            registry.load(model_name, model_file, warmup=True)
            rng0 = np.random.RandomState(11)
            probe = rng0.randn(rows_per_req, features).tolist()
            # first /explain pays the lazy dense compile + per-bucket
            # jits; warm it out of the timed window like warmup=True
            # does for the predict lane
            code, warm = _post_json(host, port, "/explain",
                                    {"model": model_name, "rows": probe})
            if code != 200:
                raise RuntimeError(f"explain prewarm -> HTTP {code}")
            # client-side context: served additivity across the HTTP
            # boundary — sum(phi) row-wise vs the raw scores the SAME
            # server serves for the SAME rows
            code, raw = _post_json(host, port, "/predict",
                                   {"model": model_name, "rows": probe,
                                    "raw_score": True})
            phi = np.asarray(warm["contributions"], np.float64)
            additive_ok = bool(
                code == 200 and np.allclose(
                    phi.sum(axis=1),
                    np.asarray(raw["predictions"], np.float64),
                    rtol=1e-4, atol=1e-4))
            # coalesced batches pad to the bucket covering the whole
            # in-flight wave (threads * rows): warm that program too or
            # its jit lands inside the timed window and pollutes p99
            wave_rows = int(threads_n) * int(rows_per_req)
            if wave_rows > rows_per_req:
                _post_json(host, port, "/explain",
                           {"model": model_name,
                            "rows": rng0.randn(
                                wave_rows, features).tolist()})

            before = parse_prometheus(scrape_metrics(host, port))
            counts = {"sent": 0, "ok": 0, "predict_sent": 0,
                      "predict_ok": 0, "errors": {}}
            lock = threading.Lock()
            stop = threading.Event()

            def scraper():
                # burn windows must sample DURING the rung
                while not stop.wait(scrape_interval_s):
                    try:
                        scrape_json(host, port, "/slo")
                    except Exception:
                        pass

            t0 = time.perf_counter()
            stop_at = t0 + duration_s

            def worker(wid):
                rng = np.random.RandomState(200 + wid)
                rows = rng.randn(rows_per_req, features).tolist()
                sent = ok = psent = pok = 0
                errors = {}
                i = 0
                while time.perf_counter() < stop_at:
                    i += 1
                    # every Nth request rides the predict lane: both
                    # lanes stay hot so the isolation claim is tested,
                    # not assumed
                    path = "/predict" if (predict_every and
                                          i % predict_every == 0) \
                        else "/explain"
                    try:
                        code, _ = _post_json(
                            host, port, path,
                            {"model": model_name, "rows": rows})
                    except Exception:
                        errors["connect"] = errors.get("connect", 0) + 1
                        continue
                    if path == "/predict":
                        psent += 1
                        pok += code == 200
                    else:
                        sent += 1
                        ok += code == 200
                    if code != 200:
                        errors[str(code)] = errors.get(str(code), 0) + 1
                with lock:
                    counts["sent"] += sent
                    counts["ok"] += ok
                    counts["predict_sent"] += psent
                    counts["predict_ok"] += pok
                    for k, v in errors.items():
                        counts["errors"][k] = \
                            counts["errors"].get(k, 0) + v

            sc = threading.Thread(target=scraper, daemon=True)
            sc.start()
            threads = [threading.Thread(target=worker, args=(w,))
                       for w in range(int(threads_n))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            stop.set()
            sc.join(2.0)
            elapsed = time.perf_counter() - t0
            after = parse_prometheus(scrape_metrics(host, port))
            slo_rep = scrape_json(host, port, "/slo")

            def delta(metric, **labels):
                return metric_sum(after, metric, **labels) - \
                    metric_sum(before, metric, **labels)

            explain_reqs = delta("lgbm_tpu_serve_explain_requests_total",
                                 model=model_name)
            resp_total = delta("lgbm_tpu_serve_explain_responses_total")
            resp_5xx = sum(
                delta("lgbm_tpu_serve_explain_responses_total", code=c)
                for c in ("500", "503", "504"))
            fallback_batches = delta(
                "lgbm_tpu_serve_explain_fallback_batches_total")
            fallback_by_reason = {
                lbl.get("reason", "?"): val for lbl, val in
                after.get("lgbm_tpu_serve_explain_fallback", ())
                if val > 0}
            per_bucket = {}
            for lbl, val in after.get(
                    "lgbm_tpu_serve_explain_latency_ms_p99", ()):
                if lbl.get("model") == model_name:
                    per_bucket[lbl.get("bucket", "?")] = {
                        "p99_ms": val,
                        "p50_ms": metric_sum(
                            after, "lgbm_tpu_serve_explain_latency_ms_p50",
                            model=model_name, bucket=lbl.get("bucket"))}
            predict_5xx = sum(
                delta("lgbm_tpu_serve_predict_responses_total", code=c)
                for c in ("500", "503", "504"))
        finally:
            srv.shutdown()

    explain_ent = next(
        (s for s in slo_rep.get("slos", ())
         if s.get("name") == "serve/explain_latency_p99"), {})
    availability = 1.0 - (resp_5xx / resp_total if resp_total else 0.0)
    slo_ok = bool(slo_rep.get("ok"))
    volume_ok = explain_reqs >= 20  # the SLO's min_events window
    dense_ok = fallback_batches == 0
    verdict = "pass" if (slo_ok and availability >= 1.0 and dense_ok and
                         volume_ok and predict_5xx == 0) else "breach"
    return {
        "schema": "explain-loadtest-report-v1",
        "git_sha": _git_sha(),
        "backend": backend,
        "verdict": verdict,
        "verdict_source": "/metrics + /slo scrapes only",
        "slo_ok": slo_ok,
        "availability": round(availability, 6),
        "dense_ok": dense_ok,
        "volume_ok": volume_ok,
        "predict_lane_clean": predict_5xx == 0,
        "explain_qps": round(explain_reqs / elapsed, 2),
        "explain_rows_per_sec": round(
            explain_reqs * rows_per_req / elapsed, 1),
        "fallback_batches": int(fallback_batches),
        "fallback_by_reason": fallback_by_reason,
        "per_bucket": per_bucket,
        "explain_slo": explain_ent,
        "additive_ok": additive_ok,
        "config": {"duration_s": duration_s, "threads": int(threads_n),
                   "rows_per_request": int(rows_per_req),
                   "features": int(features), "trees": int(trees),
                   "leaves": int(leaves),
                   "predict_every": int(predict_every),
                   "backend": backend},
        "slo": slo_rep,
        "client": counts,
    }


def explain_to_bench_matrix(report) -> dict:
    """bench-matrix-v1 rows for the nightly gate: one explain qps row,
    one p99 row per bucket, one fallback row (any drift off 0 means the
    dense compiler stopped serving and the host walk absorbed the load
    — a perf cliff the latency rows alone could survive), and the
    verdict."""
    rows = [{"name": "explain_loadtest",
             "config": report["config"],
             "qps": report["explain_qps"],
             "rows_per_sec": report["explain_rows_per_sec"],
             "availability": report["availability"],
             "interpreted": False}]
    for b, lat in sorted(report["per_bucket"].items()):
        rows.append({"name": f"explain_loadtest_p99_b{b}",
                     "config": {"bucket": b, **report["config"]},
                     "p99_ms": lat["p99_ms"],
                     "interpreted": False})
    rows.append({"name": "explain_fallbacks",
                 "config": report["config"],
                 "fallback_batches": report["fallback_batches"],
                 "interpreted": False})
    rows.append({"name": "explain_verdict",
                 "slo_ok": bool(report["slo_ok"]),
                 "verdict": report["verdict"]})
    return {
        "schema": "bench-matrix-v1",
        "bench": "explain-loadtest",
        "git_sha": report["git_sha"],
        "backend": report["backend"],
        "rows": rows,
    }


def zoo_to_bench_matrix(report) -> dict:
    """bench-matrix-v1 rows for the nightly gate: per lane one rows/s
    row and one launches-per-1k row (the stacked lane drifting toward
    the per-model launch count is a regression of the fused dispatch),
    one cold-load p99 row, and the verdict."""
    rows = []
    for lane in report["lanes"]:
        rows.append({"name": f"zoo_{lane['mode']}",
                     "config": report["config"],
                     "rows_per_sec": lane["rows_per_sec"],
                     "availability": lane["availability"],
                     "interpreted": False})
        rows.append({"name": f"zoo_{lane['mode']}_launches",
                     "config": report["config"],
                     "launches_per_1k": lane["launches_per_1k_requests"],
                     "interpreted": False})
    rows.append({"name": "zoo_cold_load",
                 "config": report["config"],
                 "p99_ms": report["lanes"][0]["cold_load_p99_ms"],
                 "interpreted": False})
    rows.append({"name": "zoo_verdict",
                 "slo_ok": report["verdict"] == "pass",
                 "verdict": report["verdict"]})
    return {
        "schema": "bench-matrix-v1",
        "bench": "zoo-loadtest",
        "git_sha": report["git_sha"],
        "backend": report["backend"],
        "rows": rows,
    }


def refresh_to_bench_matrix(report) -> dict:
    """bench-matrix-v1 rows for the nightly gate: per refresh lane one
    p99 row and one recompile row (delta lane drifting off 0 recompiles
    is a regression of the in-envelope splice), plus the verdict."""
    rows = []
    for lane in report["lanes"]:
        rows.append({"name": f"refresh_{lane['mode']}_p99",
                     "config": lane["config"],
                     "p99_ms": lane["p99_ms"],
                     "availability": lane["availability"],
                     "interpreted": False})
        rows.append({"name": f"refresh_{lane['mode']}_recompiles",
                     "config": lane["config"],
                     "recompiles": lane["recompiles"],
                     "interpreted": False})
    rows.append({"name": "refresh_verdict",
                 "slo_ok": report["verdict"] == "pass",
                 "verdict": report["verdict"]})
    return {
        "schema": "bench-matrix-v1",
        "bench": "refresh-under-load",
        "git_sha": report["git_sha"],
        "backend": report["backend"],
        "rows": rows,
    }


def fleet_chaos_to_bench_matrix(report) -> dict:
    """bench-matrix-v1 rows for the nightly regression gate: one qps
    row (throughput direction) and one SLO verdict row (a recovery that
    stops meeting the availability SLO flips met -> breached and fails
    the gate)."""
    return {
        "schema": "bench-matrix-v1",
        "bench": "fleet-chaos",
        "git_sha": report["git_sha"],
        "backend": report["backend"],
        "rows": [
            {"name": "fleet_chaos", "config": report["config"],
             "qps": report["qps"],
             "availability": report["availability"],
             "interpreted": False},
            {"name": "fleet_chaos_slo",
             "slo_ok": bool(report["slo_ok"] and report["recovered"]
                            and report["crashed"]),
             "verdict": report["verdict"]},
        ],
    }


def to_bench_matrix(report) -> dict:
    """bench-matrix-v1 record for the nightly regression gate: per rung
    one rows/s row and one qps row (each metric on its own row — the
    gate compares one key per row, so sharing a row would leave qps
    unjudged), one latency row per (rung, bucket), one SLO verdict
    row."""
    rows = []
    for r in report["rungs"]:
        rows.append({"name": f"loadtest_{r['label']}",
                     "config": r["config"],
                     "rows_per_sec": r["rows_per_sec"],
                     "availability": r["availability"],
                     "interpreted": False})
        rows.append({"name": f"loadtest_{r['label']}_qps",
                     "config": r["config"],
                     "qps": r["qps"],
                     "interpreted": False})
        for b, lat in sorted(r["per_bucket"].items()):
            rows.append({"name": f"loadtest_{r['label']}_p99_b{b}",
                         "config": {"bucket": b, **r["config"]},
                         "p99_ms": lat["p99_ms"],
                         "queue_wait_p50_ms": lat["queue_wait_p50_ms"],
                         "device_p50_ms": lat["device_p50_ms"],
                         "interpreted": False})
    rows.append({"name": "loadtest_slo",
                 "slo_ok": bool(report["slo_ok"]),
                 "verdict": report["verdict"]})
    return {
        "schema": "bench-matrix-v1",
        "bench": "loadtest",
        "git_sha": report["git_sha"],
        "backend": report["backend"],
        "rows": rows,
    }


def main(argv) -> int:
    json_path = slo_path = ""
    if "--json" in argv:
        json_path = argv[argv.index("--json") + 1]
    if "--slo-report" in argv:
        slo_path = argv[argv.index("--slo-report") + 1]

    if "--fleet-chaos" in argv:
        report = run_fleet_chaos(
            workers=int(os.environ.get("FLEET_WORKERS", 2)),
            duration_s=float(os.environ.get("FLEET_DURATION", 8.0)),
            qps=float(os.environ.get("FLEET_QPS", 30.0)),
            crash_after=int(os.environ.get("FLEET_CRASH_AFTER", 40)),
            recovery_window_s=float(
                os.environ.get("FLEET_RECOVERY_S", 10.0)))
        print(json.dumps({
            "verdict": report["verdict"],
            "crashed": report["crashed"],
            "recovered": report["recovered"],
            "slo_ok": report["slo_ok"],
            "all_requests_terminal": report["all_requests_terminal"],
            "availability": report["availability"],
            "fleet_restarts_total": report["fleet_restarts_total"],
            "fleet_retries_total": report["fleet_retries_total"]},
            indent=2), flush=True)
        if slo_path:
            with open(slo_path, "w") as fh:
                json.dump(report, fh, indent=2, default=str)
        if json_path:
            with open(json_path, "w") as fh:
                json.dump(fleet_chaos_to_bench_matrix(report), fh,
                          indent=2, default=str)
        return 0 if report["verdict"] == "pass" else 1

    if "--zoo" in argv:
        report = run_zoo_loadtest(
            models=int(os.environ.get("ZOO_MODELS", 16)),
            duration_s=float(os.environ.get("ZOO_DURATION", 5.0)),
            threads_n=int(os.environ.get("ZOO_THREADS", 24)),
            rows_per_req=int(os.environ.get("ZOO_ROWS", 4)),
            zipf_a=float(os.environ.get("ZOO_ZIPF", 1.3)),
            max_wait_ms=float(os.environ.get("ZOO_MAX_WAIT_MS", 10.0)))
        print(json.dumps({
            "verdict": report["verdict"],
            "speedup_ok": report["speedup_ok"],
            "availability_ok": report["availability_ok"],
            "cold_loads_ok": report["cold_loads_ok"],
            "rows_ratio": report["rows_ratio"],
            "launch_ratio": report["launch_ratio"],
            "lanes": [{k: l[k] for k in
                       ("mode", "rows_per_sec", "qps",
                        "launches_per_1k_requests", "cold_loads",
                        "cold_load_p99_ms", "availability")}
                      for l in report["lanes"]]}, indent=2), flush=True)
        if slo_path:
            with open(slo_path, "w") as fh:
                json.dump(report, fh, indent=2, default=str)
        if json_path:
            with open(json_path, "w") as fh:
                json.dump(zoo_to_bench_matrix(report), fh,
                          indent=2, default=str)
        return 0 if report["verdict"] == "pass" else 1

    if "--refresh" in argv:
        report = run_refresh_under_load(
            duration_s=float(os.environ.get("REFRESH_DURATION", 6.0)),
            qps=float(os.environ.get("REFRESH_QPS", 40.0)),
            base_rounds=int(os.environ.get("REFRESH_BASE_ROUNDS", 4)),
            refresh_rounds=int(os.environ.get("REFRESH_ROUNDS", 4)),
            shard=int(os.environ.get("REFRESH_SHARD", 16)))
        print(json.dumps({
            "verdict": report["verdict"],
            "delta_ok": report["delta_ok"],
            "availability_ok": report["availability_ok"],
            "full_swap_ok": report["full_swap_ok"],
            "lanes": [{k: l[k] for k in
                       ("mode", "p99_ms", "recompiles", "availability",
                        "final_round")} for l in report["lanes"]]},
            indent=2), flush=True)
        if slo_path:
            with open(slo_path, "w") as fh:
                json.dump(report, fh, indent=2, default=str)
        if json_path:
            with open(json_path, "w") as fh:
                json.dump(refresh_to_bench_matrix(report), fh,
                          indent=2, default=str)
        return 0 if report["verdict"] == "pass" else 1

    if "--explain" in argv:
        report = run_explain_loadtest(
            duration_s=float(os.environ.get("EXPLAIN_DURATION", 5.0)),
            threads_n=int(os.environ.get("EXPLAIN_THREADS", 4)),
            rows_per_req=int(os.environ.get("EXPLAIN_ROWS", 8)),
            features=int(os.environ.get("EXPLAIN_FEATURES", 6)),
            trees=int(os.environ.get("EXPLAIN_TREES", 20)),
            leaves=int(os.environ.get("EXPLAIN_LEAVES", 15)),
            predict_every=int(os.environ.get("EXPLAIN_PREDICT_EVERY", 4)),
            p99_threshold_ms=float(os.environ.get("EXPLAIN_P99_MS", 0.0)))
        print(json.dumps({
            "verdict": report["verdict"],
            "slo_ok": report["slo_ok"],
            "availability": report["availability"],
            "dense_ok": report["dense_ok"],
            "volume_ok": report["volume_ok"],
            "predict_lane_clean": report["predict_lane_clean"],
            "additive_ok": report["additive_ok"],
            "explain_qps": report["explain_qps"],
            "explain_rows_per_sec": report["explain_rows_per_sec"],
            "fallback_batches": report["fallback_batches"],
            "per_bucket": report["per_bucket"]}, indent=2), flush=True)
        if slo_path:
            with open(slo_path, "w") as fh:
                json.dump(report, fh, indent=2, default=str)
        if json_path:
            with open(json_path, "w") as fh:
                json.dump(explain_to_bench_matrix(report), fh,
                          indent=2, default=str)
        return 0 if report["verdict"] == "pass" else 1

    ladder = [tok.strip() for tok in
              os.environ.get("LOAD_LADDER", "closed").split(",")
              if tok.strip()]
    report = run_loadtest(
        ladder=ladder,
        duration_s=float(os.environ.get("LOAD_DURATION", 5.0)),
        workers=int(os.environ.get("LOAD_WORKERS", 3)),
        features=int(os.environ.get("LOAD_FEATURES", 4)),
        trees=int(os.environ.get("LOAD_TREES", 20)),
        leaves=int(os.environ.get("LOAD_LEAVES", 15)),
        bucket_mix=_parse_bucket_mix(
            os.environ.get("LOAD_BUCKETS", "4096")),
        arrival=os.environ.get("LOAD_ARRIVAL", "uniform"),
        target_rows_per_s=float(os.environ.get("LOAD_TARGET_ROWS_S", 1e5)),
        p99_threshold_ms=float(os.environ.get("LOAD_P99_MS", 0.0)),
        max_queue_rows=int(os.environ.get("LOAD_MAX_QUEUE_ROWS", 0)))

    for r in report["rungs"]:
        print(json.dumps({
            "rung": r["label"], "rows_per_sec": r["rows_per_sec"],
            "qps": r["qps"], "availability": r["availability"],
            "slo_ok": r["slo"].get("ok")}), flush=True)
    print(json.dumps({
        "verdict": report["verdict"], "slo_ok": report["slo_ok"],
        "rows_ok": report["rows_ok"],
        "peak_rows_per_sec": report["peak_rows_per_sec"],
        "target_rows_per_s": report["target_rows_per_s"]}), flush=True)

    if slo_path:
        with open(slo_path, "w") as fh:
            json.dump(report, fh, indent=2, default=str)
    if json_path:
        with open(json_path, "w") as fh:
            json.dump(to_bench_matrix(report), fh, indent=2, default=str)
    return 0 if report["verdict"] == "pass" else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
