"""Serving latency benchmark: dense-compiler vs sequential-walk matrix.

Measures the steady-state request path (pad -> jitted bucket program ->
host copy) on warm CompiledPredictors for BOTH serving programs — the
inference compiler's fused dense program (``tpu_predict_compiler=dense``)
and the sequential per-tree walk (``walk``) — per shape bucket, per
model shape (num_trees x num_leaves), with and without categorical
splits.  Every dense row carries ``speedup_vs_walk`` against the
matching walk row; one bench-matrix-v1 JSON record for the CI artifact
(next to hist_kernel.py / many_models.py).

    python benchmarks/serve_latency.py                 # print rows
    python benchmarks/serve_latency.py --json out.json # + artifact

Env knobs: LAT_SHAPES ("50x63,200x7" = trees x leaves ladder),
LAT_BUCKETS ("64,512,4096"), LAT_REQUESTS (50 timed requests/rung),
LAT_FEATURES (28), LAT_ROWS (4000 training rows), LAT_CAT ("1" = also
run the categorical variants).

On non-TPU backends the dense rows measure the same program the MXU
runs but without the hardware the formulation targets (PERF.md round 4
measured the dense/walk ratio at ~70x per tree on TPU; round 13 records
the CPU-rung inversion) — rows carry the backend so regression diffs
compare like with like.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _git_sha():
    # same shape as the sibling benchmarks' helper (full sha, None on
    # failure) so artifact records join by git_sha across benches
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=10).stdout.strip() or None
    except Exception:
        return None


def _train(trees, leaves, feats, rows, cat):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.randn(rows, feats).astype(np.float32)
    w = rng.randn(feats) / np.sqrt(feats)
    logit = X @ w
    cat_cols = []
    if cat:
        X[:, 3] = rng.randint(0, 48, rows)   # multi-word bitset (48 cats)
        logit = logit + (X[:, 3] % 3 == 0) * 1.2
        cat_cols = [3]
    y = ((logit + 0.5 * rng.randn(rows)) > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": leaves,
         "learning_rate": 0.1, "verbosity": -1}
    ds = lgb.Dataset(X, y, categorical_feature=cat_cols or "auto", params=p)
    return lgb.train(p, ds, trees)


def _measure(pred, Xq, reqs):
    """Timed requests only — callers warm the bucket first."""
    from lightgbm_tpu.telemetry.metrics import percentile as _pct
    lat = []
    for _ in range(reqs):
        t0 = time.perf_counter()
        pred.predict(Xq)
        lat.append((time.perf_counter() - t0) * 1e3)
    lat.sort()
    return _pct(lat, 50.0), _pct(lat, 99.0)


def _measure_split(pred, Xq, reqs, bucket):
    """Queue-wait vs device-compute split through the real micro-batcher
    (the per-request tracing path the serving tier runs): p50 of each
    component from the (model, bucket)-labeled timing histograms.  A
    small fixed sample suffices for a p50 split — the un-batched p50/p99
    measurement above already paid the full request count, so this must
    not double the ladder's wall time."""
    from lightgbm_tpu.serve.batcher import MicroBatcher
    from lightgbm_tpu.telemetry.metrics import percentile as _pct
    mb = MicroBatcher(pred.predict, stats=pred.stats, buckets=pred.buckets)
    try:
        for _ in range(min(int(reqs), 12)):
            mb.predict(Xq)
    finally:
        mb.close()
    t = pred.stats.bucket_timing(bucket)
    return {
        "request_p50_ms": round(_pct(t["request_latency_ms"], 50.0), 4),
        "queue_wait_p50_ms": round(_pct(t["queue_wait_ms"], 50.0), 4),
        "device_p50_ms": round(_pct(t["device_ms"], 50.0), 4),
    }


def main(argv) -> None:
    json_path = ""
    if "--json" in argv:
        json_path = argv[argv.index("--json") + 1]

    shapes = [tuple(int(v) for v in s.split("x"))
              for s in os.environ.get("LAT_SHAPES", "50x63,200x7").split(",")]
    buckets = [int(b) for b in
               os.environ.get("LAT_BUCKETS", "64,512,4096").split(",")]
    reqs = int(os.environ.get("LAT_REQUESTS", 50))
    feats = int(os.environ.get("LAT_FEATURES", 28))
    rows = int(os.environ.get("LAT_ROWS", 4000))
    with_cat = os.environ.get("LAT_CAT", "1") not in ("0", "false")

    from lightgbm_tpu.utils.backend import default_backend
    from lightgbm_tpu.utils.log import set_verbosity
    backend = default_backend()  # CPU fallback when the plugin is broken
    set_verbosity(-1)
    rng = np.random.RandomState(1)

    rows_out = []
    walk_p50 = {}
    for trees, leaves in shapes:
        for cat in ([False, True] if with_cat else [False]):
            bst = _train(trees, leaves, feats, rows, cat)
            preds = {}
            for path in ("walk", "dense"):
                try:
                    preds[path] = bst.to_predictor(warmup=False,
                                                   compiler=path)
                except Exception as e:  # noqa: BLE001 — record, keep going
                    rows_out.append({
                        "name": f"serve_{path}_{'cat' if cat else 'num'}"
                                f"_t{trees}x{leaves}",
                        "error": f"{type(e).__name__}: {e}"[:200]})
                    continue
            for bucket in buckets:
                Xq = rng.randn(bucket, feats).astype(np.float32)
                if cat:
                    Xq[:, 3] = rng.randint(0, 52, bucket)
                for path, pred in preds.items():
                    pred.predict(Xq)  # warm this bucket (unmeasured)
                    r0 = pred.stats.snapshot()["recompiles"]
                    p50, p99 = _measure(pred, Xq, reqs)
                    split = _measure_split(pred, Xq, reqs, bucket)
                    key = (trees, leaves, cat, bucket)
                    if path == "walk":
                        walk_p50[key] = p50
                    row = {
                        "name": f"serve_{path}_{'cat' if cat else 'num'}"
                                f"_t{trees}x{leaves}_b{bucket}",
                        "config": {"path": path, "cat": cat,
                                   "trees": trees, "leaves": leaves,
                                   "bucket": bucket, "features": feats,
                                   "backend": backend},
                        "p50_ms": round(p50, 4),
                        "p99_ms": round(p99, 4),
                        "rows_per_sec": round(bucket / (p50 / 1e3), 1),
                        # the per-request tracing split through the real
                        # micro-batcher path (queue wait vs device call)
                        **split,
                        "recompiles_after_warm": pred.stats.snapshot()[
                            "recompiles"] - r0,
                        "interpreted": False,
                    }
                    if path == "dense" and key in walk_p50:
                        row["speedup_vs_walk"] = round(
                            walk_p50[key] / p50, 3)
                    rows_out.append(row)
                    print(json.dumps(row), flush=True)

    if json_path:
        record = {
            "schema": "bench-matrix-v1",
            "bench": "serve_latency",
            "git_sha": _git_sha(),
            "backend": backend,
            "rows": rows_out,
        }
        with open(json_path, "w") as f:
            json.dump(record, f, indent=2)
        print(json.dumps({"written": json_path, "rungs": len(rows_out)}),
              flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])
