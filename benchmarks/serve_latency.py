"""Serving latency benchmark: p50/p99 per shape bucket on a warm
CompiledPredictor, one bench.py-schema JSON line per bucket.

Measures the steady-state request path (pad -> jitted bucket program ->
host copy) that the /predict endpoint pays per micro-batch, after
ahead-of-time warmup — so the numbers are recompile-free by construction
(asserted via the stats counter).

    python benchmarks/serve_latency.py           # all ladder buckets
    LAT_REQUESTS=200 python benchmarks/serve_latency.py

Env knobs: LAT_TREES (50), LAT_LEAVES (63), LAT_FEATURES (28),
LAT_REQUESTS (100 timed requests per bucket), LAT_ROWS (20000 training
rows).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    trees = int(os.environ.get("LAT_TREES", 50))
    leaves = int(os.environ.get("LAT_LEAVES", 63))
    feats = int(os.environ.get("LAT_FEATURES", 28))
    reqs = int(os.environ.get("LAT_REQUESTS", 100))
    rows = int(os.environ.get("LAT_ROWS", 20000))

    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve import SHAPE_BUCKETS
    from lightgbm_tpu.telemetry.metrics import percentile as _pct
    from lightgbm_tpu.utils.backend import default_backend
    from lightgbm_tpu.utils.log import set_verbosity

    backend = default_backend()  # CPU fallback when the plugin is broken
    set_verbosity(-1)
    rng = np.random.RandomState(0)
    X = rng.randn(rows, feats).astype(np.float32)
    w = rng.randn(feats) / np.sqrt(feats)
    y = ((X @ w + 0.5 * rng.randn(rows)) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": leaves,
              "learning_rate": 0.1, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(X, y, params=params), trees)
    pred = bst.to_predictor(warmup=True)
    recompiles0 = pred.stats.snapshot()["recompiles"]

    for bucket in SHAPE_BUCKETS:
        Xq = rng.randn(bucket, feats).astype(np.float32)
        pred.predict(Xq)  # one unmeasured run per bucket (cache touch)
        lat = []
        for _ in range(reqs):
            t0 = time.perf_counter()
            pred.predict(Xq)
            lat.append((time.perf_counter() - t0) * 1e3)
        lat.sort()
        print(json.dumps({
            "metric": f"serve_latency_p50_ms (bucket {bucket}, {trees} "
                      f"trees, {leaves} leaves, {backend})",
            "value": round(_pct(lat, 50.0), 4),
            "unit": "ms",
            "p99_ms": round(_pct(lat, 99.0), 4),
            "rows_per_sec": round(bucket / (_pct(lat, 50.0) / 1e3), 1),
        }), flush=True)

    recompiled = pred.stats.snapshot()["recompiles"] - recompiles0
    print(json.dumps({
        "metric": "serve_recompiles_after_warmup",
        "value": recompiled,
        "unit": "count",
    }))


if __name__ == "__main__":
    main()
