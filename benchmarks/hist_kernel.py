"""Histogram-kernel micro-benchmark: the impl/variant x B x row_block
ladder, in bench-matrix-v1 records.

Promoted from scripts/bench_hist.py (which now delegates here).  Each
rung measures ONE full histogram build — the op that dominates training
(PERF.md) — and reports builds/s plus effective streamed GB/s
(bins + weight rows in, histogram out).  Variants:

* ``segment`` / ``onehot`` / ``packed4`` — the XLA formulations
  (ops/histogram.py); ``packed4`` is the joint-nibble scatter that
  halves scatter volume for max_bin<=16 data (B=16 rungs only).
* ``pallas`` / ``pallas:blockspec`` / ``pallas:packed4`` — the Pallas
  kernel pipelines (ops/histogram_pallas.py): DMA double-buffered
  streaming (default), the v1 BlockSpec fetch, and the DMA + 4-bit
  packed-bin layout.  Off-TPU these run the INTERPRETER (a correctness
  proxy, ~1000x slow) and are capped at PALLAS_ROWS rows — their
  builds/s are recorded with ``interpreted: true`` and excluded from
  speedup claims.

    JAX_PLATFORMS=cpu SCALE=1.0 python benchmarks/hist_kernel.py \
        --json hist-kernel.json

Env knobs: SCALE (rows multiplier), ROWS (default 1<<20), FEATURES (28),
B_LADDER ("16,64,255"), ROW_BLOCKS ("4096"), REPS (3),
PALLAS_ROWS (16384 off-TPU), SKIP_PALLAS=1 to drop the interpret rungs.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCALE = float(os.environ.get("SCALE", 1.0))
ROWS = max(4096, int(int(os.environ.get("ROWS", 1 << 20)) * SCALE) // 4096 * 4096)
FEATURES = int(os.environ.get("FEATURES", 28))
B_LADDER = tuple(int(b) for b in
                 os.environ.get("B_LADDER", "16,64,255").split(","))
ROW_BLOCKS = tuple(int(r) for r in
                   os.environ.get("ROW_BLOCKS", "4096").split(","))
REPS = int(os.environ.get("REPS", 3))
PALLAS_ROWS = int(os.environ.get("PALLAS_ROWS", 16384))
SKIP_PALLAS = os.environ.get("SKIP_PALLAS", "") == "1"


def _git_sha():
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=10).stdout.strip() or None
    except Exception:
        return None


def _timeit(fn, reps):
    import jax
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main(argv):
    json_path = None
    if "--json" in argv:
        i = argv.index("--json")
        json_path = argv[i + 1]
        del argv[i:i + 2]

    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import build_histogram
    from lightgbm_tpu.ops.histogram_pallas import (build_histogram_pallas,
                                                   pack_bins4, pad_rows)
    from lightgbm_tpu.utils.backend import default_backend

    backend = default_backend()
    on_tpu = backend == "tpu"
    pallas_rows = ROWS if on_tpu else min(ROWS, max(4096, PALLAS_ROWS))
    rng = np.random.RandomState(0)
    rows_out = []
    baseline_bps = {}   # (B, rows) -> builds/s of the baseline impl

    for B in B_LADDER:
        bins = rng.randint(0, B, (ROWS, FEATURES)).astype(np.uint8)
        grad = rng.randn(ROWS).astype(np.float32)
        hess = np.abs(rng.randn(ROWS)).astype(np.float32)
        mask = (rng.rand(ROWS) < 0.8).astype(np.float32)
        bins_d = jnp.asarray(bins)
        g, h, m = map(jnp.asarray, (grad, hess, mask))

        xla_impls = ["segment", "onehot"] + (["packed4"] if B <= 16 else [])
        baseline_impl = "onehot" if on_tpu else "segment"
        for impl in xla_impls:
            def run(impl=impl):
                return build_histogram(bins_d, g, h, m, num_bins=B,
                                       impl=impl)
            dt = _timeit(run, REPS)
            bps = 1.0 / dt
            streamed = ROWS * FEATURES + ROWS * 12 + FEATURES * B * 12
            if impl == baseline_impl:
                baseline_bps[(B, ROWS)] = bps
            rows_out.append({
                "name": f"hist_{impl}_B{B}",
                "config": {"impl": impl, "num_bins": B, "rows": ROWS,
                           "features": FEATURES, "row_block": 0},
                "builds_per_sec": round(bps, 4),
                "gbytes_per_sec": round(streamed * bps / 1e9, 3),
                "interpreted": False,
            })
            print(json.dumps(rows_out[-1]), flush=True)

        if SKIP_PALLAS:
            continue
        n_p = pad_rows(pallas_rows)
        bins_t = jnp.asarray(
            np.pad(bins[:pallas_rows], ((0, n_p - pallas_rows),
                                        (0, 0))).T.copy())
        gp = jnp.asarray(np.pad(grad[:pallas_rows], (0, n_p - pallas_rows)))
        hp = jnp.asarray(np.pad(hess[:pallas_rows], (0, n_p - pallas_rows)))
        mp = jnp.asarray(np.pad(mask[:pallas_rows], (0, n_p - pallas_rows)))
        pk = pack_bins4(bins_t) if B <= 16 else None
        variants = [("pallas", dict(pipeline="dma")),
                    ("pallas:blockspec", dict(pipeline="blockspec"))]
        if B <= 16:
            variants.append(("pallas:packed4", dict(bins_packed=True)))
        for rb in ROW_BLOCKS:
            if n_p % rb:
                continue
            for name, kw in variants:
                src = pk if kw.get("bins_packed") else bins_t

                def run(src=src, kw=kw, rb=rb):
                    return build_histogram_pallas(src, gp, hp, mp,
                                                  num_bins=B, row_block=rb,
                                                  **kw)
                try:
                    dt = _timeit(run, REPS)
                except Exception as e:  # noqa: BLE001 — record the failure
                    rows_out.append({
                        "name": f"hist_{name}_B{B}_rb{rb}",
                        "config": {"impl": name, "num_bins": B,
                                   "rows": n_p, "features": FEATURES,
                                   "row_block": rb},
                        "error": f"{type(e).__name__}: {e}"[:200],
                    })
                    continue
                bps = 1.0 / dt
                bin_bytes = FEATURES * (n_p // 2 if kw.get("bins_packed")
                                        else n_p)
                streamed = bin_bytes + n_p * 16 + FEATURES * B * 12
                rows_out.append({
                    "name": f"hist_{name}_B{B}_rb{rb}",
                    "config": {"impl": name, "num_bins": B, "rows": n_p,
                               "features": FEATURES, "row_block": rb},
                    "builds_per_sec": round(bps, 4),
                    "gbytes_per_sec": round(streamed * bps / 1e9, 3),
                    "interpreted": not on_tpu,
                })
                print(json.dumps(rows_out[-1]), flush=True)

    # speedups vs the backend's default impl at the same (B, rows) —
    # interpret-mode pallas rungs are correctness proxies, not claims
    for r in rows_out:
        key = (r["config"]["num_bins"], r["config"]["rows"])
        base = baseline_bps.get(key)
        if base and not r.get("interpreted") and "builds_per_sec" in r:
            r["speedup_vs_baseline"] = round(r["builds_per_sec"] / base, 3)

    if json_path:
        record = {
            "schema": "bench-matrix-v1",
            "bench": "hist_kernel",
            "git_sha": _git_sha(),
            "backend": backend,
            "scale": SCALE,
            "rows": rows_out,
        }
        with open(json_path, "w") as f:
            json.dump(record, f, indent=2)
        print(json.dumps({"written": json_path,
                          "rungs": len(rows_out)}), flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])
