"""Benchmark harness over the BASELINE.json config matrix.

Reproduces the five reference benchmark shapes (docs/Experiments.rst +
BASELINE.json "configs") on synthetic data at a configurable scale, each
printing one JSON line in bench.py's schema.  The repo-root ``bench.py``
remains the driver-run headline (Higgs single-chip); this harness covers
the rest of the matrix:

    python benchmarks/run.py                 # all configs, SCALE=1
    python benchmarks/run.py higgs ranking   # subset
    SCALE=0.1 python benchmarks/run.py       # 10x smaller (CI/smoke)

Configs:
  higgs      10.5M x 28 dense binary, 255 leaves/bins (Experiments.rst:110)
  higgs_dp   same, tree_learner=data over all visible devices
  ranking    LambdaRank, MSLR-like query structure, feature-parallel
  multiclass Covertype-like 7-class + categoricals, GOSS
  sparse     Criteo-like wide one-hot sparse, EFB + voting-parallel

``--json out.json`` additionally writes one machine-trackable record for
the whole run (schema ``bench-matrix-v1``: git sha, backend, SCALE, and
the per-config name/config/iters_per_sec rows), so the perf trajectory
lands in BENCH_*.json-style artifacts instead of being hand-copied into
PERF.md.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCALE = float(os.environ.get("SCALE", 1.0))

# rows accumulated for the --json record (one per benched config)
_RECORDS = []


def _git_sha():
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=10).stdout.strip() or None
    except Exception:
        return None


def _emit(name, trees, dt, extra="", baseline=None, config=None):
    """One bench.py-schema JSON line.  ``baseline`` is the reference
    iters/s for THIS config when published (docs/Experiments.rst); the
    non-Higgs configs have no comparable published number and omit
    vs_baseline rather than ratio against a different workload."""
    ips = trees / dt
    rec = {
        "metric": f"boosting_iters_per_sec ({name}{extra})",
        "value": round(ips, 4),
        "unit": "iters/s",
    }
    if baseline:
        rec["vs_baseline"] = round(ips / baseline, 4)
    print(json.dumps(rec), flush=True)
    _RECORDS.append({
        "name": name,
        "iters_per_sec": round(ips, 4),
        "trees": trees,
        "seconds": round(dt, 3),
        **({"vs_baseline": round(ips / baseline, 4)} if baseline else {}),
        **({"config": config} if config else {}),
    })


HIGGS_CPU_BASELINE = 500.0 / 130.094   # == bench.py BASELINE_ITERS_PER_SEC


def _train(params, ds, trees, valid=None, warmup=1):
    import lightgbm_tpu as lgb
    bst = lgb.Booster(params=params, train_set=ds)
    for _ in range(warmup):           # compile + first tree(s); GOSS
        bst.update()                  # configs warm past the 1/lr
    #                                   sampling boundary so its one-time
    #                                   recompile stays out of steady-state
    t0 = time.perf_counter()
    for _ in range(trees):
        bst.update()
    float(np.asarray(bst._gbdt.score).sum())
    return bst, time.perf_counter() - t0


def bench_higgs(tree_learner="serial"):
    import lightgbm_tpu as lgb
    n = int(10_500_000 * SCALE)
    rng = np.random.RandomState(0)
    X = rng.randn(n, 28).astype(np.float32)
    w = rng.randn(28) / np.sqrt(28)
    y = ((X @ w + 0.3 * np.sin(2 * X[:, 0]) * X[:, 1] +
          0.5 * rng.randn(n)) > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
         "learning_rate": 0.1, "verbosity": -1,
         "tree_learner": tree_learner}
    trees = int(os.environ.get("TREES", 25))
    _, dt = _train(p, lgb.Dataset(X, y, params=p), trees)
    _emit("higgs" if tree_learner == "serial" else "higgs_dp", trees, dt,
          f", {n}x28, tl={tree_learner}",
          # the published number is for the FULL 10.5M config only
          baseline=HIGGS_CPU_BASELINE if SCALE == 1.0 else None,
          config={**p, "rows": n, "features": 28})


def bench_ranking():
    import lightgbm_tpu as lgb
    nq = int(3000 * SCALE) or 10
    per_q = 120
    n = nq * per_q
    rng = np.random.RandomState(1)
    X = rng.randn(n, 64).astype(np.float32)
    w = rng.randn(64) / 8
    rel = X @ w + 0.7 * rng.randn(n)
    group = np.full(nq, per_q)
    y = np.zeros(n)
    for q in range(nq):  # per-query 5-level relevance
        s = rel[q * per_q:(q + 1) * per_q]
        y[q * per_q:(q + 1) * per_q] = np.digitize(
            s, np.quantile(s, [0.5, 0.75, 0.9, 0.97]))
    p = {"objective": "lambdarank", "num_leaves": 255, "max_bin": 255,
         "learning_rate": 0.1, "verbosity": -1,
         "tree_learner": "feature"}
    trees = int(os.environ.get("TREES", 25))
    ds = lgb.Dataset(X, y, group=group, params=p)
    _, dt = _train(p, ds, trees)
    _emit("ranking_lambdarank", trees, dt, f", {nq} queries, tl=feature",
          config={**p, "queries": nq, "rows": n, "features": 64})


def bench_multiclass():
    import lightgbm_tpu as lgb
    n = int(581_000 * SCALE) or 5000
    rng = np.random.RandomState(2)
    Xn = rng.randn(n, 10).astype(np.float32)
    cat = rng.randint(0, 40, (n, 2)).astype(np.float32)
    X = np.concatenate([Xn, cat], axis=1)
    logits = np.stack([Xn @ (rng.randn(10) / 3) +
                       (cat[:, 0] % 7 == c) * 1.5 for c in range(7)], 1)
    y = np.argmax(logits + 0.5 * rng.randn(n, 7), axis=1).astype(np.float64)
    p = {"objective": "multiclass", "num_class": 7, "num_leaves": 63,
         "max_bin": 255, "learning_rate": 0.1, "verbosity": -1,
         "boosting": "goss"}
    trees = int(os.environ.get("TREES", 10))
    ds = lgb.Dataset(X, y, categorical_feature=[10, 11], params=p)
    _, dt = _train(p, ds, trees, warmup=int(1.0 / p["learning_rate"]) + 2)
    _emit("multiclass_goss", trees, dt, f", {n}x12 7-class",
          config={**p, "rows": n, "features": 12})


def bench_sparse():
    import scipy.sparse as sp
    import lightgbm_tpu as lgb
    n = int(1_000_000 * SCALE) or 10_000
    f = 2000
    rng = np.random.RandomState(3)
    nnz_per_row = 25
    rows = np.repeat(np.arange(n), nnz_per_row)
    cols = rng.randint(0, f, n * nnz_per_row)
    vals = rng.rand(n * nnz_per_row).astype(np.float32) + 0.5
    X = sp.csr_matrix((vals, (rows, cols)), shape=(n, f))
    y = ((np.asarray(X[:, :50].sum(axis=1)).ravel() +
          0.5 * rng.randn(n)) > 12.5).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 127, "max_bin": 255,
         "learning_rate": 0.1, "verbosity": -1,
         "tree_learner": "voting"}
    trees = int(os.environ.get("TREES", 10))
    ds = lgb.Dataset(X, y, params=p)
    _, dt = _train(p, ds, trees)
    _emit("sparse_voting_efb", trees, dt, f", {n}x{f} 98.75%-sparse",
          config={**p, "rows": n, "features": f})


ALL = {
    "higgs": lambda: bench_higgs("serial"),
    "higgs_dp": lambda: bench_higgs("data"),
    "ranking": bench_ranking,
    "multiclass": bench_multiclass,
    "sparse": bench_sparse,
}


def main():
    from lightgbm_tpu.utils.log import set_verbosity
    set_verbosity(-1)
    argv = list(sys.argv[1:])
    json_path = None
    telemetry_path = None
    if "--json" in argv:
        i = argv.index("--json")
        if i + 1 >= len(argv):
            sys.exit("usage: run.py [configs...] --json OUT.json "
                     "[--telemetry OUT.json]")
        json_path = argv[i + 1]
        del argv[i:i + 2]
    if "--telemetry" in argv:
        i = argv.index("--telemetry")
        if i + 1 >= len(argv):
            sys.exit("usage: run.py [configs...] --json OUT.json "
                     "[--telemetry OUT.json]")
        telemetry_path = argv[i + 1]
        del argv[i:i + 2]
    which = argv or list(ALL)
    for name in which:
        ALL[name]()
    if telemetry_path:
        # metrics registry + last benched config's TrainRecord (per-phase
        # seconds, hist passes, collective tallies) — the CI artifact
        from lightgbm_tpu.telemetry import write_snapshot
        write_snapshot(telemetry_path)
        print(json.dumps({"written": telemetry_path,
                          "kind": "telemetry-snapshot-v1"}), flush=True)
    if json_path:
        from lightgbm_tpu.utils.backend import default_backend
        record = {
            "schema": "bench-matrix-v1",
            "git_sha": _git_sha(),
            "backend": default_backend(),
            "scale": SCALE,
            "rows": _RECORDS,
        }
        with open(json_path, "w") as f:
            json.dump(record, f, indent=2)
        print(json.dumps({"written": json_path,
                          "configs": len(_RECORDS)}), flush=True)


if __name__ == "__main__":
    main()
