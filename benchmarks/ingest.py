"""Out-of-core ingest benchmark: the source x chunk_rows ladder, in
bench-matrix-v1 records.

Each rung streams a synthetic/mmap/CSV source through the full
StreamedDataset construct (sketch pass + bin/spill pass) and reports
rows/s plus effective host->spill GB/s; the chunked-training rungs
additionally measure host->HBM streamed GB/s per full histogram pass
(the bytes-per-pass budget PERF.md round 12 tracks).  At sizes that
also fit in core (<= INCORE_CAP rows) the in-core ``Dataset.construct``
is timed on identical data for a ``speedup_vs_incore`` column (usually
< 1 — streaming trades wall time for the O(rows) raw matrix it never
allocates; the point of the ladder is that streamed cost per row stays
FLAT as rows grow past what in-core can hold at all).

    JAX_PLATFORMS=cpu ROWS=1000000 python benchmarks/ingest.py \
        --json ingest.json

Env knobs: ROWS (default 1<<20), FEATURES (16), CHUNK_LADDER
("65536,262144"), SOURCES ("synthetic,mmap"), TRAIN_ROUNDS (2; 0 skips
the training rungs), INCORE_CAP (4<<20).
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROWS = int(os.environ.get("ROWS", 1 << 20))
FEATURES = int(os.environ.get("FEATURES", 16))
CHUNK_LADDER = tuple(int(c) for c in
                     os.environ.get("CHUNK_LADDER", "65536,262144").split(","))
SOURCES = tuple(os.environ.get("SOURCES", "synthetic,mmap").split(","))
TRAIN_ROUNDS = int(os.environ.get("TRAIN_ROUNDS", 2))
INCORE_CAP = int(os.environ.get("INCORE_CAP", 4 << 20))

_PARAMS = {"objective": "binary", "verbosity": -1, "max_bin": 63,
           "num_leaves": 31, "enable_bundle": False,
           "use_quantized_grad": True, "stochastic_rounding": False,
           "tree_grow_mode": "wave", "tpu_exact_endgame": False,
           "tpu_speculative_ramp": False}


def _git_sha():
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=10).stdout.strip() or None
    except Exception:
        return None


def _make_source(kind, rows, chunk_rows, workdir):
    from lightgbm_tpu.ingest import (CSVSource, NumpyMmapSource,
                                     SyntheticSource)
    if kind == "synthetic":
        return SyntheticSource(rows, FEATURES, chunk_rows=chunk_rows, seed=1)
    syn = SyntheticSource(rows, FEATURES, chunk_rows=max(CHUNK_LADDER),
                          seed=1)
    if kind == "mmap":
        xp = os.path.join(workdir, f"x_{rows}.npy")
        yp = os.path.join(workdir, f"y_{rows}.npy")
        if not os.path.exists(xp):
            X = np.lib.format.open_memmap(
                xp, mode="w+", dtype=np.float64, shape=(rows, FEATURES))
            Y = np.lib.format.open_memmap(
                yp, mode="w+", dtype=np.float64, shape=(rows,))
            for c in syn.chunks():
                X[c.offset:c.offset + len(c.X)] = c.X
                Y[c.offset:c.offset + len(c.X)] = c.label
            X.flush()
            Y.flush()
            del X, Y
        return NumpyMmapSource(xp, yp, chunk_rows=chunk_rows)
    if kind == "csv":
        path = os.path.join(workdir, f"d_{rows}.csv")
        if not os.path.exists(path):
            with open(path, "w") as fh:
                for c in syn.chunks():
                    for i in range(len(c.X)):
                        fh.write(f"{c.label[i]:g}," + ",".join(
                            f"{v:.9g}" for v in c.X[i]) + "\n")
        return CSVSource(path, chunk_rows=chunk_rows)
    raise ValueError(f"unknown source kind {kind}")


def main(argv):
    json_path = None
    if "--json" in argv:
        i = argv.index("--json")
        json_path = argv[i + 1]
        del argv[i:i + 2]

    import lightgbm_tpu as lgb
    from lightgbm_tpu.ingest import StreamedDataset, train_streamed
    from lightgbm_tpu.telemetry.metrics import default_registry
    from lightgbm_tpu.utils.backend import default_backend

    rows_out = []
    workdir = tempfile.mkdtemp(prefix="lgbm_ingest_bench_")
    incore_dt = None
    if ROWS <= INCORE_CAP:
        syn = _make_source("synthetic", ROWS, max(CHUNK_LADDER), workdir)
        X = np.concatenate([c.X for c in syn.chunks()])
        y = np.concatenate([c.label for c in syn.chunks()])
        t0 = time.perf_counter()
        lgb.Dataset(X, label=y, params=_PARAMS).construct()
        incore_dt = time.perf_counter() - t0
        rows_out.append({
            "name": "construct_incore",
            "config": {"source": "incore", "rows": ROWS,
                       "features": FEATURES, "chunk_rows": 0},
            "rows_per_sec": round(ROWS / incore_dt, 1),
            "raw_bytes_resident": ROWS * FEATURES * 8,
        })
        print(json.dumps(rows_out[-1]), flush=True)
        del X, y

    for kind in SOURCES:
        for chunk_rows in CHUNK_LADDER:
            if chunk_rows > ROWS:
                continue
            src = _make_source(kind, ROWS, chunk_rows, workdir)
            spill = os.path.join(workdir, f"spill_{kind}_{chunk_rows}")
            t0 = time.perf_counter()
            sd = StreamedDataset(src, params=_PARAMS,
                                 spill_dir=spill).construct()
            dt = time.perf_counter() - t0
            spill_bytes = os.path.getsize(
                os.path.join(spill, "binned.dat"))
            rec = {
                "name": f"construct_{kind}_c{chunk_rows}",
                "config": {"source": kind, "rows": ROWS,
                           "features": FEATURES, "chunk_rows": chunk_rows},
                "rows_per_sec": round(ROWS / dt, 1),
                "gbytes_per_sec": round(ROWS * FEATURES * 8 / dt / 1e9, 3),
                "spill_bytes": spill_bytes,
            }
            if incore_dt is not None:
                rec["speedup_vs_incore"] = round(incore_dt / dt, 3)
            rows_out.append(rec)
            print(json.dumps(rec), flush=True)

            if TRAIN_ROUNDS > 0 and kind == SOURCES[0]:
                reg = default_registry()
                ctr = reg.counter("ingest_train_h2d_bytes_total", "")
                b0 = ctr.value()
                t0 = time.perf_counter()
                bst = train_streamed(_PARAMS, sd,
                                     num_boost_round=TRAIN_ROUNDS)
                dt = time.perf_counter() - t0
                passes = sum(int(t.num_leaves) > 1
                             for t in bst._gbdt.models)
                h2d = ctr.value() - b0
                rec = {
                    "name": f"train_chunked_{kind}_c{chunk_rows}",
                    "config": {"source": kind, "rows": ROWS,
                               "features": FEATURES,
                               "chunk_rows": chunk_rows,
                               "rounds": TRAIN_ROUNDS},
                    "iters_per_sec": round(TRAIN_ROUNDS / dt, 4),
                    "h2d_gbytes_total": round(h2d / 1e9, 3),
                    "h2d_gbytes_per_sec": round(h2d / dt / 1e9, 3),
                    "trees": passes,
                }
                rows_out.append(rec)
                print(json.dumps(rec), flush=True)

    if json_path:
        record = {
            "schema": "bench-matrix-v1",
            "bench": "ingest",
            "git_sha": _git_sha(),
            "backend": default_backend(),
            "rows": rows_out,
        }
        with open(json_path, "w") as f:
            json.dump(record, f, indent=2)
        print(json.dumps({"written": json_path,
                          "rungs": len(rows_out)}), flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])
