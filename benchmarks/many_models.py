"""Many-models throughput bench: ``train_many`` vs sequential ``train``.

Measures models/sec at 100k x 28 (scaled by ``SCALE``) for a ladder of
batch widths M, against a sequential-train() baseline extrapolated from
``SEQ_SAMPLES`` standalone runs (every train() is independent and the
compiled grower is shared through the grow-fn cache, so per-model
sequential time is constant after the first call).  Emits one
``bench-matrix-v1`` record (``--json out.json``) with a
``speedup_vs_sequential`` column per M — the ISSUE 7 acceptance series.

    JAX_PLATFORMS=cpu SCALE=0.05 python benchmarks/many_models.py \
        --json many_models.json

Defaults to the acceptance geometry (100k x 28, 31 leaves, 20 rounds,
M up to 64); SCALE shrinks rows for CI smoke runs.

The lifted-variant rungs (PR 20) repeat the ladder per boosting family
that used to be a structural fallback — goss / dart / multiclass /
ranking — on 1k-row models at ``FAM_M_LADDER`` widths, each against its
own sequential baseline (rows ``many_models_{family}_M{M}``).  Set
``FAMILIES=`` to skip them.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCALE = float(os.environ.get("SCALE", 1.0))
ROUNDS = int(os.environ.get("ROUNDS", 20))
SEQ_SAMPLES = int(os.environ.get("SEQ_SAMPLES", 3))
M_LADDER = tuple(int(m) for m in
                 os.environ.get("M_LADDER", "1,8,16,64").split(","))
FAM_M_LADDER = tuple(int(m) for m in
                     os.environ.get("FAM_M_LADDER", "8,32").split(","))
FAMILIES = tuple(f for f in
                 os.environ.get("FAMILIES",
                                "goss,dart,multiclass,ranking").split(",")
                 if f)
FAM_ROUNDS = int(os.environ.get("FAM_ROUNDS", 20))
FAM_N = int(os.environ.get("FAM_N", 1000))   # acceptance: 1k-row models

N, F = max(1000, int(100_000 * SCALE)), 28
PARAMS = {"objective": "regression", "num_leaves": 31,
          "learning_rate": 0.1, "verbosity": -1}

# Each lifted family sweeps only HOST_SWEEP knobs so the whole ladder
# stays one batched program (num_groups == 1 asserted below).
FAMILY_SPECS = {
    "goss": {"params": {"objective": "binary", "boosting": "goss",
                        "learning_rate": 0.5, "num_leaves": 31,
                        "verbosity": -1},
             "task": "binary",
             "variant": lambda i: {"top_rate": 0.15 + 0.01 * (i % 8),
                                   "other_rate": 0.05 + 0.01 * (i % 5)}},
    "dart": {"params": {"objective": "binary", "boosting": "dart",
                        "drop_rate": 0.1, "num_leaves": 31,
                        "learning_rate": 0.1, "verbosity": -1},
             "task": "binary",
             "variant": lambda i: {"drop_seed": 100 + i,
                                   "drop_rate": 0.05 + 0.02 * (i % 5)}},
    "multiclass": {"params": {"objective": "multiclass", "num_class": 3,
                              "num_leaves": 31, "learning_rate": 0.1,
                              "verbosity": -1},
                   "task": "mc",
                   "variant": lambda i: {"lambda_l2": 0.1 * i}},
    "ranking": {"params": {"objective": "lambdarank", "num_leaves": 31,
                           "learning_rate": 0.1, "verbosity": -1},
                "task": "rank",
                "variant": lambda i: {"lambda_l2": 0.1 * i}},
}


def _family_data(task, n, f=F, seed=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    raw = X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(n)
    groups = None
    if task == "binary":
        y = (raw > 0).astype(np.float64)
    elif task == "mc":
        y = np.digitize(raw, [-0.5, 0.5]).astype(np.float64)
    else:                                      # rank: graded relevance
        y = np.clip(np.round(raw + 2), 0, 4).astype(np.float64)
        groups = [30] * (n // 30)
        groups[-1] += n - sum(groups)
    return X, y, groups


def _git_sha():
    try:
        return subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=10).stdout.strip() or None
    except Exception:
        return None


def main(argv):
    json_path = None
    if "--json" in argv:
        i = argv.index("--json")
        json_path = argv[i + 1]
        del argv[i:i + 2]

    import lightgbm_tpu as lgb
    from lightgbm_tpu.multitrain import train_many

    rng = np.random.RandomState(0)
    X = rng.randn(N, F).astype(np.float32)
    y = X[:, 0] + 0.5 * X[:, 1] * X[:, 2] + 0.1 * rng.randn(N)
    ds = lgb.Dataset(X, y)
    ds.construct(lgb.Config(PARAMS))

    def variant(i):
        return {"lambda_l2": 0.1 * i}

    # warm both compile paths out of the timed regions
    lgb.train({**PARAMS, **variant(990)}, ds, 2)
    train_many(PARAMS, ds, num_boost_round=2,
               variants=[variant(991), variant(992)])

    t0 = time.time()
    for i in range(SEQ_SAMPLES):
        lgb.train({**PARAMS, **variant(900 + i)}, ds, ROUNDS)
    seq_per_model = (time.time() - t0) / SEQ_SAMPLES
    seq_models_per_sec = 1.0 / seq_per_model
    print(json.dumps({"metric": "sequential_models_per_sec",
                      "value": round(seq_models_per_sec, 4),
                      "rows": N, "features": F, "rounds": ROUNDS}),
          flush=True)

    rows = []
    for M in M_LADDER:
        t0 = time.time()
        mb = train_many(PARAMS, ds, num_boost_round=ROUNDS,
                        variants=[variant(i) for i in range(M)])
        dt = time.time() - t0
        assert len(mb) == M and not mb.fallback_indices
        mps = M / dt
        speedup = mps / seq_models_per_sec
        rec = {"metric": f"train_many_models_per_sec (M={M})",
               "value": round(mps, 4),
               "speedup_vs_sequential": round(speedup, 3),
               "batch_seconds": round(dt, 2),
               "rows": N, "features": F, "rounds": ROUNDS,
               "num_leaves": PARAMS["num_leaves"]}
        print(json.dumps(rec), flush=True)
        rows.append({"name": f"many_models_M{M}",
                     "config": {**PARAMS, "M": M, "rounds": ROUNDS,
                                "rows": N, "features": F},
                     "models_per_sec": round(mps, 4),
                     "speedup_vs_sequential": round(speedup, 3)})

    for fam in FAMILIES:
        spec = FAMILY_SPECS[fam]
        fparams, fvariant = spec["params"], spec["variant"]
        Xf, yf, groups = _family_data(spec["task"], FAM_N)
        fds = lgb.Dataset(Xf, yf, group=groups)
        fds.construct(lgb.Config(fparams))

        lgb.train({**fparams, **fvariant(990)}, fds, 2)
        train_many(fparams, fds, num_boost_round=2,
                   variants=[fvariant(991), fvariant(992)])

        t0 = time.time()
        for i in range(SEQ_SAMPLES):
            lgb.train({**fparams, **fvariant(900 + i)}, fds, FAM_ROUNDS)
        fam_seq_per_sec = SEQ_SAMPLES / (time.time() - t0)

        for M in FAM_M_LADDER:
            fvars = [fvariant(i) for i in range(M)]
            # warm this batch width's compile out of the timed region:
            # at 1k rows a fresh M-wide grower compile would dominate
            # the 20-round run (the sequential baseline's compile is
            # equally cached by its warm-up above)
            train_many(fparams, fds, num_boost_round=2, variants=fvars)
            t0 = time.time()
            mb = train_many(fparams, fds, num_boost_round=FAM_ROUNDS,
                            variants=fvars)
            dt = time.time() - t0
            assert len(mb) == M and not mb.fallback_indices, \
                f"{fam}: lifted family fell back ({mb.fallback_indices})"
            assert mb.num_groups == 1, \
                f"{fam}: sweep split into {mb.num_groups} batches"
            mps = M / dt
            speedup = mps / fam_seq_per_sec
            rec = {"metric": f"train_many_{fam}_models_per_sec (M={M})",
                   "value": round(mps, 4),
                   "speedup_vs_sequential": round(speedup, 3),
                   "batch_seconds": round(dt, 2),
                   "rows": FAM_N, "features": F, "rounds": FAM_ROUNDS}
            print(json.dumps(rec), flush=True)
            rows.append({"name": f"many_models_{fam}_M{M}",
                         "config": {**fparams, "M": M,
                                    "rounds": FAM_ROUNDS,
                                    "rows": FAM_N, "features": F},
                         "models_per_sec": round(mps, 4),
                         "speedup_vs_sequential": round(speedup, 3)})

    if json_path:
        from lightgbm_tpu.utils.backend import default_backend
        record = {
            "schema": "bench-matrix-v1",
            "git_sha": _git_sha(),
            "backend": default_backend(),
            "scale": SCALE,
            "sequential_models_per_sec": round(seq_models_per_sec, 4),
            "rows": rows,
        }
        with open(json_path, "w") as f:
            json.dump(record, f, indent=2)
        print(json.dumps({"written": json_path, "ladder": list(M_LADDER)}),
              flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])
