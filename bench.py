"""Benchmark: boosting iterations/sec on Higgs-shaped data.

Reproduces the reference's headline config (docs/Experiments.rst:110 —
Higgs 10.5M x 28, 500 trees, 255 leaves, 255 bins, lr 0.1; reference CPU:
130.094 s => 3.84 iters/s on 2x E5-2690v4; see BASELINE.md) on synthetic
Higgs-like data, on whatever single device JAX provides (the driver runs
this on one real TPU chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Measurement: 2 warmup updates (compile + cache), then BENCH_WINDOWS
timed windows of trees with ONE device-forcing scalar sync each; the
headline value is the MEDIAN window rate — the run-to-run variance of the
shared axon tunnel (±20-40%, PERF.md) hits individual windows, not the
median.  The JSON also carries per-window rates and an on-chip kernel
self-check: the Pallas q8 / bf16 histogram kernels vs the XLA onehot
path on 1M real rows (int path must be exactly 0).

Env knobs: BENCH_ROWS (default 10_500_000 — the BASELINE's true scale),
BENCH_TREES (default 50), BENCH_WINDOWS (5), BENCH_LEAVES (255),
BENCH_BINS (255), BENCH_QUANT (default 1: int8 quantized-gradient
histograms at 254 levels with stochastic rounding + exact leaf renewal —
the TPU configuration of the reference's own use_quantized_grad feature;
set 0 for exact bf16 hi/lo histograms), BENCH_SELFCHECK (default 1).
"""

import json
import os
import statistics
import time

import numpy as np

BASELINE_ITERS_PER_SEC = 500.0 / 130.094  # reference Higgs CPU number


def kernel_selfcheck(gbdt) -> dict:
    """Pallas kernels vs the XLA onehot path on up to 1M real rows."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.histogram import build_histogram_leaves
    from lightgbm_tpu.ops.histogram_pallas import (
        LEAF_CHANNELS, Q_LEAF_CHANNELS, build_histogram_pallas_leaves,
        build_histogram_pallas_leaves_q8, pack_weights8)

    X_T = getattr(gbdt.learner, "_XpT", None)         # (F, N) device bins
    if X_T is None:
        X_T = jnp.swapaxes(gbdt.X_dev, 0, 1)
    n_all = X_T.shape[1]
    n = min(1_048_576, n_all // 4096 * 4096)
    if n == 0:
        return {}
    bins_t = X_T[:, :n]
    bins_rows = jnp.swapaxes(bins_t, 0, 1)
    B = 256  # covers every u8 bin code incl. the NaN bin
    rng = np.random.RandomState(0)
    out = {}

    # int8 quantized kernel: exact integer sums — diff MUST be 0
    ch_q = jnp.asarray(
        rng.randint(-1, Q_LEAF_CHANNELS, size=n).astype(np.int8))
    wch = jnp.asarray(np.concatenate([
        rng.randint(-127, 128, size=(1, n)),
        rng.randint(0, 128, size=(1, n)),
        np.ones((1, n)), np.zeros((5, n))]).astype(np.int8))
    hq = build_histogram_pallas_leaves_q8(bins_t, wch, ch_q, num_bins=B)
    hx = build_histogram_leaves(
        bins_rows, wch[0].astype(jnp.float32), wch[1].astype(jnp.float32),
        jnp.ones((n,), jnp.float32), ch_q,
        num_channels=Q_LEAF_CHANNELS, num_bins=B, impl="onehot")
    dq = jnp.max(jnp.abs(hq.astype(jnp.float32) - jnp.round(hx)))
    out["kernel_q8_max_abs_diff"] = float(dq)

    # bf16 hi/lo kernel: exact to f32 accumulation-order differences
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.asarray(rng.rand(n).astype(np.float32))
    ones = jnp.ones((n,), jnp.float32)
    ch_b = jnp.asarray(rng.randint(-1, LEAF_CHANNELS, size=n)
                       .astype(np.int8))
    hb = build_histogram_pallas_leaves(bins_t, pack_weights8(
        grad, hess, ones), ch_b, num_bins=B)
    hxb = build_histogram_leaves(
        bins_rows, grad, hess, ones, ch_b,
        num_channels=LEAF_CHANNELS, num_bins=B, impl="onehot")
    scale = jnp.maximum(1.0, jnp.max(jnp.abs(hxb)))
    out["kernel_bf16_max_rel_diff"] = float(
        jnp.max(jnp.abs(hb - hxb)) / scale)
    return out


def main() -> None:
    import lightgbm_tpu  # noqa: F401 — import before any jax client use
    from lightgbm_tpu.utils.backend import default_backend

    # resolve the backend FIRST: when the TPU plugin raises UNAVAILABLE
    # this pins the platform to CPU (with a warning) instead of letting
    # the first jitted op crash the whole benchmark run
    backend = default_backend()
    try:
        _run(backend)
    except Exception as exc:  # noqa: BLE001
        if backend == "tpu":
            raise
        # TPU-less host: the bench must still exit 0 with ONE valid JSON
        # record so the harness records a CPU-fallback datapoint instead
        # of a zeroed round (BENCH_r05's failure mode)
        print(json.dumps({
            "metric": "boosting_iters_per_sec",
            "value": 0.0, "unit": "iters/s", "vs_baseline": 0.0,
            "backend": backend, "cpu_fallback": True,
            "error": f"{type(exc).__name__}: {exc}",
        }))


def _run(backend: str) -> None:
    cpu_fallback = backend != "tpu"
    if cpu_fallback:
        # smoke-scale defaults off-TPU (the flagship 10.5M x 28 shape
        # would run for hours on XLA:CPU); explicit BENCH_* env knobs
        # still win
        rows = int(os.environ.get("BENCH_ROWS", 65_536))
        trees = int(os.environ.get("BENCH_TREES", 6))
        leaves = int(os.environ.get("BENCH_LEAVES", 63))
        selfcheck_default = 0  # Pallas kernels need the TPU toolchain
    else:
        rows = int(os.environ.get("BENCH_ROWS", 10_500_000))
        trees = int(os.environ.get("BENCH_TREES", 50))
        leaves = int(os.environ.get("BENCH_LEAVES", 255))
        selfcheck_default = 1
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", 5)))
    bins = int(os.environ.get("BENCH_BINS", 255))

    import jax.numpy as jnp
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.log import set_verbosity

    set_verbosity(-1)
    rng = np.random.RandomState(0)
    f = 28
    # Higgs-like: dense floats, binary label with learnable structure
    X = rng.randn(rows, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    logit = X @ w + 0.3 * np.sin(2 * X[:, 0]) * X[:, 1]
    y = (logit + rng.randn(rows) * 0.5 > 0).astype(np.float64)

    params = {
        "objective": "binary", "num_leaves": leaves, "max_bin": bins,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
    }
    quant = int(os.environ.get("BENCH_QUANT", 1))
    if quant:
        params.update({"use_quantized_grad": True,
                       "num_grad_quant_bins": 254,
                       "quant_train_renew_leaf": True})
    ds = lgb.Dataset(X, y, params=params)
    booster = lgb.Booster(params=params, train_set=ds)

    def sync():
        # ONE scalar host copy forces every queued device computation
        # (block_until_ready alone can lie through the axon tunnel)
        return float(jnp.sum(booster._gbdt.score))

    # warmup: compile + first trees (the second update also exercises the
    # donation/steady path once before any timed window)
    booster.update()
    booster.update()
    sync()

    per_window = max(1, trees // windows)
    rates = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(per_window):
            booster.update()
        sync()
        rates.append(per_window / (time.perf_counter() - t0))
    iters_per_sec = statistics.median(rates)

    extra = {}
    if int(os.environ.get("BENCH_SELFCHECK", selfcheck_default)):
        extra = kernel_selfcheck(booster._gbdt)
    # full-data histogram passes of the last tree (wave grower counter;
    # the exact-endgame + spec-ramp target is <=7 at 255 leaves)
    passes = getattr(booster._gbdt, "last_hist_passes", None)
    if passes is not None and int(passes) > 0:  # 0 = non-wave grower
        extra["hist_passes_per_tree"] = int(passes)

    print(json.dumps({
        "metric": f"boosting_iters_per_sec (binary, {rows}x{f}, "
                  f"{leaves} leaves, {bins} bins"
                  f"{', quantized-grad int8' if quant else ''}, "
                  f"{backend})",
        "value": round(iters_per_sec, 4),
        "unit": "iters/s",
        "vs_baseline": round(iters_per_sec / BASELINE_ITERS_PER_SEC, 4),
        "window_rates": [round(r, 4) for r in rates],
        "backend": backend,
        "cpu_fallback": cpu_fallback,
        **extra,
    }))


if __name__ == "__main__":
    main()
