"""Benchmark: boosting iterations/sec on Higgs-shaped data.

Reproduces the reference's headline config (docs/Experiments.rst:110 —
Higgs 10.5M x 28, 500 trees, 255 leaves, 255 bins, lr 0.1; reference CPU:
130.094 s => 3.84 iters/s on 2x E5-2690v4; see BASELINE.md) on synthetic
Higgs-like data, on whatever single device JAX provides (the driver runs
this on one real TPU chip).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Env knobs: BENCH_ROWS (default 10_500_000 — the BASELINE's true scale),
BENCH_TREES (default 50), BENCH_LEAVES (255), BENCH_BINS (255),
BENCH_QUANT (default 1: int8 quantized-gradient histograms at 254 levels
with stochastic rounding + exact leaf renewal — the TPU configuration of
the reference's own use_quantized_grad feature, LightGBM 4.x gradient
quantization; set 0 for exact bf16 hi/lo histograms).  iters/sec is
steady-state (compile and first-tree warmup excluded).
"""

import json
import os
import sys
import time

import numpy as np

BASELINE_ITERS_PER_SEC = 500.0 / 130.094  # reference Higgs CPU number


def main() -> None:
    rows = int(os.environ.get("BENCH_ROWS", 10_500_000))
    trees = int(os.environ.get("BENCH_TREES", 50))
    leaves = int(os.environ.get("BENCH_LEAVES", 255))
    bins = int(os.environ.get("BENCH_BINS", 255))

    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.log import set_verbosity

    set_verbosity(-1)
    rng = np.random.RandomState(0)
    f = 28
    # Higgs-like: dense floats, binary label with learnable structure
    X = rng.randn(rows, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    logit = X @ w + 0.3 * np.sin(2 * X[:, 0]) * X[:, 1]
    y = (logit + rng.randn(rows) * 0.5 > 0).astype(np.float64)

    params = {
        "objective": "binary", "num_leaves": leaves, "max_bin": bins,
        "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
    }
    quant = int(os.environ.get("BENCH_QUANT", 1))
    if quant:
        params.update({"use_quantized_grad": True,
                       "num_grad_quant_bins": 254,
                       "quant_train_renew_leaf": True})
    ds = lgb.Dataset(X, y, params=params)
    booster = lgb.Booster(params=params, train_set=ds)

    # warmup: compile + first tree
    booster.update()
    t0 = time.perf_counter()
    for _ in range(trees):
        booster.update()
    # force completion of async dispatch
    float(np.asarray(booster._gbdt.score).sum())
    dt = time.perf_counter() - t0

    iters_per_sec = trees / dt
    print(json.dumps({
        "metric": f"boosting_iters_per_sec (binary, {rows}x{f}, "
                  f"{leaves} leaves, {bins} bins"
                  f"{', quantized-grad int8' if quant else ''}, "
                  f"{jax.default_backend()})",
        "value": round(iters_per_sec, 4),
        "unit": "iters/s",
        "vs_baseline": round(iters_per_sec / BASELINE_ITERS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
