"""Find a Mosaic-supported all-i8 one-hot build, then time full variants."""
import functools
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

QC = 3


def tiny(name, kernel, inputs, out_shape):
    try:
        out = pl.pallas_call(kernel, out_shape=out_shape)(*inputs)
        _ = np.asarray(jnp.ravel(out)[:1])
        print(f"  {name}: OK")
        return True
    except Exception as e:
        msg = "".join(traceback.format_exception_only(type(e), e))
        print(f"  {name}: FAIL {msg.splitlines()[0][:110]}")
        return False


def bisect():
    r = 256
    rng = np.random.RandomState(0)
    u8 = jnp.asarray(rng.randint(0, 255, (8, r)).astype(np.uint8))
    i32 = jax.ShapeDtypeStruct((256, r), jnp.int32)

    def consume(o_ref, x):
        o_ref[...] = jnp.sum(x.astype(jnp.int32), axis=0,
                             keepdims=True) + jnp.zeros(
                                 o_ref.shape, jnp.int32)

    def k_iota_i8(u_ref, o_ref):
        io = jax.lax.broadcasted_iota(jnp.int8, (256, r), 0)
        consume(o_ref, io)
    tiny("broadcasted_iota i8", k_iota_i8, (u8,), i32)

    def k_iota_cvt(u_ref, o_ref):
        io = (jax.lax.broadcasted_iota(jnp.int32, (256, r), 0)
              % 256).astype(jnp.int8)
        consume(o_ref, io)
    tiny("iota i32 -> astype i8", k_iota_cvt, (u8,), i32)

    def k_rep_u8(u_ref, o_ref):
        rep = jnp.repeat(u_ref[...], 32, axis=0)
        consume(o_ref, rep)
    tiny("repeat u8", k_rep_u8, (u8,), i32)

    def k_cmp_u8(u_ref, o_ref):
        rep = jnp.repeat(u_ref[...], 32, axis=0)
        io = (jax.lax.broadcasted_iota(jnp.int32, (256, r), 0)
              % 256).astype(jnp.uint8)
        consume(o_ref, (rep == io).astype(jnp.int8))
    tiny("cmp u8==u8 -> i8", k_cmp_u8, (u8,), i32)

    def k_cmp_i8(u_ref, o_ref):
        rep = pltpu.bitcast(jnp.repeat(u_ref[...], 32, axis=0), jnp.int8)
        io = (jax.lax.broadcasted_iota(jnp.int32, (256, r), 0)
              % 256).astype(jnp.int8)
        consume(o_ref, (rep == io).astype(jnp.int8))
    tiny("bitcast->i8 cmp", k_cmp_i8, (u8,), i32)

    def k_cmp_i8b(u_ref, o_ref):
        rep = jnp.repeat(u_ref[...].astype(jnp.int8), 32, axis=0)
        io = (jax.lax.broadcasted_iota(jnp.int32, (256, r), 0)
              % 256).astype(jnp.int8)
        consume(o_ref, (rep == io).astype(jnp.int8))
    tiny("astype u8->i8 cmp", k_cmp_i8b, (u8,), i32)

    def k_where_i8(u_ref, o_ref):
        rep = jnp.repeat(u_ref[...].astype(jnp.int8), 32, axis=0)
        io = (jax.lax.broadcasted_iota(jnp.int32, (256, r), 0)
              % 256).astype(jnp.int8)
        oh = jnp.where(rep == io, jnp.int8(1), jnp.int8(0))
        consume(o_ref, oh)
    tiny("where i8 const", k_where_i8, (u8,), i32)


# --- timed full kernels -----------------------------------------------------

def make_kernel(mode, b, group, ft):
    nk = ft // group

    def kern(bins_ref, wch_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        wch = wch_ref[...]
        r = wch.shape[0]
        ch = wch[:, 3:4].astype(jnp.int32)
        lane = jax.lax.broadcasted_iota(jnp.int32, (r, 128), 1)
        sel = (ch == lane // QC).astype(jnp.int32)
        w3 = wch[:, :QC].astype(jnp.int32)
        wtile = jnp.concatenate([w3] * (128 // QC + 1), axis=1)[:, :128]
        w128 = (wtile * sel).astype(jnp.int8)

        if mode == "i8":
            iota_gb = (jax.lax.broadcasted_iota(jnp.int32, (group * b, r),
                                                0) % b).astype(jnp.int8)
            for k in range(nk):
                cols = bins_ref[k * group:(k + 1) * group, :].astype(
                    jnp.int8)
                colrep = jnp.repeat(cols, b, axis=0)
                onehot = (colrep == iota_gb).astype(jnp.int8)
                part = jax.lax.dot_general(
                    onehot, w128, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                out_ref[k * group * b:(k + 1) * group * b] += part
        elif mode == "i32":
            iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, r),
                                               0) % b
            for k in range(nk):
                cols = bins_ref[k * group:(k + 1) * group, :].astype(
                    jnp.int32)
                colrep = jnp.repeat(cols, b, axis=0)
                onehot = (colrep == iota_gb).astype(jnp.int8)
                part = jax.lax.dot_general(
                    onehot, w128, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                out_ref[k * group * b:(k + 1) * group * b] += part
        return

    return kern


@functools.partial(jax.jit, static_argnames=("num_bins", "kr", "mode",
                                             "group"))
def q8(bins_t, wch, *, num_bins, kr=1024, mode="i8", group=2):
    f, n = bins_t.shape
    b = -(-num_bins // 64) * 64
    ft = -(-f // max(group, 8)) * max(group, 8)
    if ft != f:
        bins_t = jnp.pad(bins_t, ((0, ft - f), (0, 0)))
    grid = (1, n // kr)
    return pl.pallas_call(
        make_kernel(mode, b, group, ft),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kr, 8), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ft * b, 128), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * ft * b * n * 128,
            bytes_accessed=ft * n + n * 8 + ft * b * 512,
            transcendentals=0),
    )(bins_t, wch)


def timeit(fn, *args, reps=5, **kw):
    out = fn(*args, **kw)
    _ = np.asarray(jnp.ravel(out)[:1])
    t0 = time.perf_counter()
    for _i in range(reps):
        out = fn(*args, **kw)
        _ = np.asarray(jnp.ravel(out)[:1])
    return (time.perf_counter() - t0) / reps, out


def main():
    print("=== op bisect ===")
    bisect()

    n, f, b = 4_194_304, 28, 255
    rng = np.random.RandomState(0)
    bins = rng.randint(0, b, (f, n)).astype(np.uint8)
    gq = rng.randint(-127, 128, n).astype(np.int8)
    hq = rng.randint(0, 128, n).astype(np.int8)
    ch = rng.randint(-1, 42, n).astype(np.int8)
    wch = np.stack([gq, hq, np.ones(n, np.int8), ch] +
                   [np.zeros(n, np.int8)] * 4, axis=-1)
    wch[ch < 0, :3] = 0
    bins_d, wch_d = jnp.asarray(bins), jnp.asarray(wch)

    print("=== timed ===")
    for mode in ("i8", "i32"):
        for group, kr in ((2, 1024), (2, 2048), (4, 1024), (8, 1024),
                          (8, 2048)):
            try:
                t, out = timeit(q8, bins_d, wch_d, num_bins=b, kr=kr,
                                mode=mode, group=group)
                print(f"{mode:4s} g={group} kr={kr:5d}: {t*1e3:8.2f} ms",
                      flush=True)
            except Exception as e:
                print(f"{mode:4s} g={group} kr={kr:5d}: FAIL {str(e)[:90]}",
                      flush=True)

    # correctness of i8 vs i32 mode
    try:
        o1 = np.asarray(q8(bins_d, wch_d, num_bins=b, mode="i8"))
        o2 = np.asarray(q8(bins_d, wch_d, num_bins=b, mode="i32"))
        print("i8 vs i32 max diff:", np.abs(o1 - o2).max())
    except Exception as e:
        print("cmp FAIL", str(e)[:90])


if __name__ == "__main__":
    main()
