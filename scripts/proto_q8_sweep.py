"""Sweep q8 kernel variants: kr/group, dot-only vs build-only split."""
import functools
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

QC = 3
QLEAVES = 128 // QC


def _round_up(x, m):
    return -(-x // m) * m


def make_kernel(mode):
    def kern(bins_ref, w_ref, ch_ref, out_ref, *, num_features, num_bins,
             group, fstep):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        w = w_ref[...]
        ch = ch_ref[...]
        r = w.shape[0]
        b = num_bins
        lane = jax.lax.broadcasted_iota(jnp.int32, (r, 128), 1)
        sel = (ch == lane // QC).astype(jnp.int32)
        w3 = w[:, :QC].astype(jnp.int32)
        wtile = jnp.concatenate([w3] * (128 // QC + 1), axis=1)[:, :128]
        w128 = (wtile * sel).astype(jnp.int8)
        iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, r), 0) % b

        def do(i, carry):
            f0 = i * fstep
            cols_blk = bins_ref[pl.ds(f0, fstep), :].astype(jnp.int32)
            for k in range(fstep // group):
                cols = cols_blk[k * group:(k + 1) * group]
                if mode == "dot_only":
                    onehot = (iota_gb < 1).astype(jnp.int8)
                elif mode == "bcast":
                    c3 = jax.lax.broadcast_in_dim(cols, (group, b, r),
                                                  (0, 2))
                    i3 = jax.lax.broadcasted_iota(jnp.int32, (group, b, r),
                                                  1)
                    onehot = (c3 == i3).astype(jnp.int8).reshape(
                        group * b, r)
                else:
                    colrep = jnp.repeat(cols, b, axis=0)
                    onehot = (colrep == iota_gb).astype(jnp.int8)
                if mode == "build_only":
                    out_ref[pl.ds((f0 + k * group) * b, group * b)] += (
                        jnp.sum(onehot.astype(jnp.int32), axis=1,
                                keepdims=True) +
                        jnp.zeros((group * b, 128), jnp.int32))
                else:
                    part = jax.lax.dot_general(
                        onehot, w128, (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.int32)
                    out_ref[pl.ds((f0 + k * group) * b, group * b)] += part
            return carry

        jax.lax.fori_loop(0, num_features // fstep, do, 0)
    return kern


@functools.partial(jax.jit,
                   static_argnames=("num_bins", "kr", "mode", "group_ovr"))
def q8(bins_t, w4, ch, *, num_bins, kr=1024, mode="repeat", group_ovr=0):
    f, n = bins_t.shape
    b = _round_up(num_bins, 64)
    group = group_ovr or 2
    fstep = max(group, 8)
    ft_cap = max(fstep, 8192 // b // fstep * fstep)
    ft = min(_round_up(f, fstep), ft_cap)
    f_pad = _round_up(f, ft)
    if f_pad != f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - f), (0, 0)))
    grid = (f_pad // ft, n // kr)
    out = pl.pallas_call(
        functools.partial(make_kernel(mode), num_features=ft, num_bins=b,
                          group=group, fstep=fstep),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kr, 4), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kr, 1), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f_pad * b, 128), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * f_pad * b * n * 128,
            bytes_accessed=f_pad * n + n * 8 + f_pad * b * 512,
            transcendentals=0),
    )(bins_t, w4, ch.astype(jnp.int32)[:, None])
    return out


def timeit(fn, *args, reps=5, **kw):
    out = fn(*args, **kw)
    _ = np.asarray(jnp.ravel(out)[:1])
    t0 = time.perf_counter()
    for _i in range(reps):
        out = fn(*args, **kw)
        _ = np.asarray(jnp.ravel(out)[:1])
    return (time.perf_counter() - t0) / reps, out


def main():
    n, f, b = 4_194_304, 28, 255
    rng = np.random.RandomState(0)
    bins = rng.randint(0, b, (f, n)).astype(np.uint8)
    gq = rng.randint(-127, 128, n).astype(np.int8)
    hq = rng.randint(0, 128, n).astype(np.int8)
    ch = rng.randint(-1, QLEAVES, n).astype(np.int32)
    w4 = np.stack([gq, hq, np.ones(n, np.int8),
                   np.zeros(n, np.int8)], axis=-1)
    w4[ch < 0] = 0
    bins_d, w4_d, ch_d = jnp.asarray(bins), jnp.asarray(w4), jnp.asarray(ch)

    for mode in ("repeat", "bcast", "dot_only", "build_only"):
        for kr in (1024, 4096, 8192):
            try:
                t, _ = timeit(q8, bins_d, w4_d, ch_d, num_bins=b, kr=kr,
                              mode=mode)
                print(f"{mode:11s} kr={kr:5d}: {t*1e3:8.2f} ms", flush=True)
            except Exception as e:
                print(f"{mode:11s} kr={kr:5d}: FAIL {str(e)[:120]}",
                      flush=True)
    for g in (4, 8):
        for kr in (4096, 8192):
            try:
                t, _ = timeit(q8, bins_d, w4_d, ch_d, num_bins=b, kr=kr,
                              mode="repeat", group_ovr=g)
                print(f"group={g} kr={kr:5d}: {t*1e3:8.2f} ms", flush=True)
            except Exception as e:
                print(f"group={g} kr={kr:5d}: FAIL {str(e)[:120]}",
                      flush=True)


if __name__ == "__main__":
    main()
