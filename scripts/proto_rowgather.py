"""Prototype: Pallas DMA row-gather kernel vs XLA gather (perf triage).

Gathers M rows of a (N, W) u8 matrix by an index vector using pipelined
per-row async DMAs — the TPU-native DataPartition row mover.
"""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, W = 10_502_144, 48
M = 1 << 20
BR = 2048


def _kernel(idx_hbm, P_hbm, out_hbm, idx_smem, sem_idx, sem_rows):
    i = pl.program_id(0)
    cp = pltpu.make_async_copy(idx_hbm.at[pl.ds(i * BR, BR)], idx_smem,
                               sem_idx)
    cp.start()
    cp.wait()

    def issue(j, _):
        pltpu.make_async_copy(P_hbm.at[idx_smem[j]],
                              out_hbm.at[i * BR + j], sem_rows).start()
        return 0

    jax.lax.fori_loop(0, BR, issue, 0)

    def drain(j, _):
        pltpu.make_async_copy(P_hbm.at[0], out_hbm.at[0], sem_rows).wait()
        return 0

    jax.lax.fori_loop(0, BR, drain, 0)


@jax.jit
def row_gather(P, idx):
    return pl.pallas_call(
        _kernel,
        grid=(M // BR,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        out_shape=jax.ShapeDtypeStruct((M, W), jnp.uint8),
        scratch_shapes=[pltpu.SMEM((BR,), jnp.int32),
                        pltpu.SemaphoreType.DMA(()),
                        pltpu.SemaphoreType.DMA(())],
    )(idx, P)


def force(out):
    return int(np.asarray(out[0, 0]))


rng = np.random.RandomState(0)
P = jnp.asarray(rng.randint(0, 255, (N, W)).astype(np.uint8))
idx_np = rng.permutation(N)[:M].astype(np.int32)
idx = jnp.asarray(idx_np)

out = row_gather(P, idx)
force(out)
# correctness
ref = np.asarray(P)[idx_np[:1000]]
np.testing.assert_array_equal(np.asarray(out[:1000]), ref)
print("correct", flush=True)

t0 = time.perf_counter()
for _ in range(3):
    out = row_gather(P, idx)
force(out)
print(f"pallas row_gather 1M rows: {(time.perf_counter() - t0) / 3 * 1000:.1f}"
      f" ms", flush=True)

xg = jax.jit(lambda P, p: P[p])
force(xg(P, idx))
t0 = time.perf_counter()
for _ in range(3):
    out = xg(P, idx)
force(out)
print(f"xla gather 1M rows: {(time.perf_counter() - t0) / 3 * 1000:.1f} ms",
      flush=True)
