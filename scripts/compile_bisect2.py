"""Compile-time probes: scan ops at 10.5M (perf triage)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

N = 10_502_144


def mark(s, t0):
    print(f"{s}: {time.perf_counter() - t0:.1f}s", flush=True)


def ff(marker):
    return jax.lax.associative_scan(lambda a, b: jnp.where(b < 0, a, b),
                                    marker)


t0 = time.perf_counter()
jax.jit(ff).lower(jnp.zeros((N,), jnp.int32)).compile()
mark("associative_scan fwd-fill N=10.5M", t0)


def cm(x):
    return jnp.cumsum(x)


t0 = time.perf_counter()
jax.jit(cm).lower(jnp.zeros((N,), jnp.int32)).compile()
mark("cumsum N=10.5M", t0)
