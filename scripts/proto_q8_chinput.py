"""Probe: leaf-channel as a separate (1, N) i8 kernel input vs the
per-wave wch row write."""
import functools
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lightgbm_tpu.ops.histogram_pallas import build_histogram_pallas_leaves_q8

QC = 3


def _round_up(x, m):
    return -(-x // m) * m


def make_kernel(b, group, ft):
    nk = ft // group

    def kern(bins_ref, w_ref, ch_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        w = w_ref[...]                        # (8, R) i8 (static channels)
        ch = ch_ref[...].astype(jnp.int32)    # (1, R)
        r = w.shape[1]
        subl = jax.lax.broadcasted_iota(jnp.int32, (128, r), 0)
        sel = (ch == subl // QC).astype(jnp.int32)
        w3 = w[:QC, :].astype(jnp.int32)
        wtile = jnp.concatenate([w3] * (128 // QC + 1), axis=0)[:128]
        w128t = (wtile * sel).astype(jnp.int8)
        iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, r), 0) % b
        for k in range(nk):
            cols = bins_ref[k * group:(k + 1) * group, :].astype(jnp.int32)
            colrep = jnp.repeat(cols, b, axis=0)
            onehot = (colrep == iota_gb).astype(jnp.int8)
            part = jax.lax.dot_general(
                onehot, w128t, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            out_ref[k * group * b:(k + 1) * group * b] += part
        return

    return kern


@functools.partial(jax.jit, static_argnames=("num_bins", "kr", "group"))
def q8_chin(bins_t, w_fm, ch, *, num_bins, kr=4096, group=8):
    f, n = bins_t.shape
    b = _round_up(num_bins, 64)
    ft = _round_up(f, max(group, 8))
    if ft != f:
        bins_t = jnp.pad(bins_t, ((0, ft - f), (0, 0)))
    grid = (1, n // kr)
    return pl.pallas_call(
        make_kernel(b, group, ft),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, kr), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kr), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ft * b, 128), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * ft * b * n * 128,
            bytes_accessed=ft * n + n * 9 + ft * b * 512,
            transcendentals=0),
    )(bins_t, w_fm, ch)


def timed(name, fn, *args, reps=10, **kw):
    try:
        out = fn(*args, **kw)
        _ = float(jnp.ravel(out)[0])
    except Exception as e:
        print(f"{name:28s} FAIL {str(e)[:90]}", flush=True)
        return None
    t0 = time.perf_counter()
    for _i in range(reps):
        out = fn(*args, **kw)
    _ = float(jnp.ravel(out)[0])
    print(f"{name:28s} {(time.perf_counter()-t0)/reps*1e3:9.2f} ms",
          flush=True)
    return out


def main():
    n, f, b = 10_502_144, 28, 255
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, b, (f, n)).astype(np.uint8))
    ch_np = rng.randint(-1, 42, n).astype(np.int8)
    wch_np = np.zeros((8, n), np.int8)
    wch_np[0] = rng.randint(-127, 128, n)
    wch_np[1] = rng.randint(0, 128, n)
    wch_np[2] = 1
    wch_np[3] = ch_np
    wch = jnp.asarray(wch_np)
    w_static = jnp.asarray(np.concatenate([wch_np[:3], np.zeros((5, n),
                                                                np.int8)]))
    ch = jnp.asarray(ch_np)[None, :]

    # A: production (ch inside wch) + the .at[3].set cost it implies
    @jax.jit
    def prod_with_set(w, c):
        w2 = w.at[3].set(c[0])
        return build_histogram_pallas_leaves_q8(bins, w2, c[0], num_bins=b)
    timed("A prod (set + kernel)", prod_with_set, wch, ch)
    timed("A2 prod kernel only",
          lambda: build_histogram_pallas_leaves_q8(bins, wch, jnp.asarray(ch_np), num_bins=b))

    # B: ch as separate (1, N) input — no per-wave wch write at all
    o = timed("B ch-input kernel", q8_chin, bins, w_static, ch, num_bins=b)
    if o is not None:
        ref = build_histogram_pallas_leaves_q8(bins, wch, jnp.asarray(ch_np), num_bins=b)
        got = np.asarray(o)[:f * 256].reshape(f, 256, 128)[
            :, :b, :126].reshape(f, b, 42, 3).transpose(2, 0, 1, 3)
        print("max diff vs prod:", np.abs(got - np.asarray(ref)).max())


if __name__ == "__main__":
    main()
