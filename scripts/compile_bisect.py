"""Standalone compile-time bisect for the 10.5M-row grower (perf triage)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

N, W, F, B = 10_502_144, 48, 28, 256
CH = 1 << 20


def mark(s, t0):
    print(f"{s}: {time.perf_counter() - t0:.1f}s", flush=True)


P = jnp.zeros((N, W), jnp.uint8)


def scat(P, pos, seg):
    return P.at[pos].set(seg, mode="drop")


t0 = time.perf_counter()
f1 = jax.jit(scat).lower(P, jnp.zeros((CH,), jnp.int32),
                         jnp.zeros((CH, W), jnp.uint8)).compile()
mark("1. scatter (1M,48)u8 -> (N,48)", t0)

from lightgbm_tpu.ops.histogram_pallas import build_histogram_pallas

t0 = time.perf_counter()
f2 = jax.jit(lambda x, g, h, m: build_histogram_pallas(
    x, g, h, m, num_bins=B)).lower(
    jnp.zeros((F, CH), jnp.uint8), jnp.zeros((CH,), jnp.float32),
    jnp.zeros((CH,), jnp.float32), jnp.zeros((CH,), jnp.float32)).compile()
mark("2. pallas hist (28,1M)", t0)


def part(P, start):
    seg = jax.lax.dynamic_slice(P, (start, 0), (CH, W))
    col = seg[:, 0].astype(jnp.int32)
    gl = col <= 3
    cl = jnp.cumsum(gl.astype(jnp.int32))
    pos = jnp.where(gl, cl - 1, N)
    return P.at[pos].set(seg, mode="drop")


t0 = time.perf_counter()
f3 = jax.jit(part).lower(P, jnp.asarray(5, jnp.int32)).compile()
mark("3. slice+cumsum+scatter chunk", t0)


def sweep(P, start, cnt):
    def body(i, acc):
        seg = jax.lax.dynamic_slice(P, (start + i * CH, 0), (CH, W))
        return acc + jnp.sum(seg[:, :F].astype(jnp.float32))

    return jax.lax.fori_loop(0, cnt // CH, body, 0.0)


t0 = time.perf_counter()
f4 = jax.jit(sweep).lower(P, jnp.asarray(0, jnp.int32),
                          jnp.asarray(N, jnp.int32)).compile()
mark("4. fori sweep of slices", t0)
