"""Multichip dryrun + collective-bytes snapshot for CI.

Runs the driver's ``dryrun_multichip`` (every parallel learner compiled
and executed on an N-virtual-CPU-device mesh, DP == serial parity
asserted) and then traces the DP wave grower in BOTH histogram-merge
modes to record the scatter-vs-allreduce byte budget from the telemetry
collective tally — so the ratio the round-8 optimisation claims
(PERF.md) is tracked per push as a CI artifact.

Usage: python scripts/multichip_dryrun.py [--devices 8] [--out multichip.json]
"""

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def collective_bytes_snapshot(n_devices: int) -> dict:
    """Trace the DP wave grower with scatter on/off and diff the
    telemetry collective tallies (trace-time, no execution needed)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from lightgbm_tpu.learner.wave import make_wave_grow_fn
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.parallel.data_parallel import (
        DataParallelTreeLearner, WaveDPStrategy)
    from lightgbm_tpu.parallel.mesh import get_mesh, shard_map_compat
    from lightgbm_tpu.parallel.voting_parallel import WaveVotingStrategy
    from lightgbm_tpu.telemetry.train_record import (collectives_reset,
                                                     collectives_snapshot)

    f, b, n = 8, 64, n_devices * 4096
    top_k = 2                        # 2k=4 < F=8: real voted filtering
    rng = np.random.RandomState(0)
    args = (jnp.asarray(rng.randint(0, b - 1, (f, n)).astype(np.uint8)),
            jnp.asarray(rng.randn(n).astype(np.float32)),
            jnp.ones((n,), jnp.float32), jnp.ones((n,), jnp.float32),
            jnp.full((f,), b, jnp.int32), jnp.zeros((f,), bool),
            jnp.zeros((f,), bool), jnp.zeros((f,), jnp.int32),
            jnp.zeros((f,), jnp.float32), jnp.ones((f,), bool))
    mesh = get_mesh(n_devices)
    ax = mesh.axis_names[0]
    sp = SplitParams(min_data_in_leaf=5, min_sum_hessian_in_leaf=0.0,
                     any_cat=False)
    out = {}
    strategies = {
        "scatter": WaveDPStrategy(ax, nshards=n_devices,
                                  hist_scatter=True),
        "allreduce": WaveDPStrategy(ax, nshards=n_devices),
        "voting": WaveVotingStrategy(ax, nshards=n_devices, top_k=top_k),
    }
    for mode, strategy in strategies.items():
        grow = make_wave_grow_fn(
            num_leaves=15, num_features=f, max_bins=b, max_depth=0,
            split_params=sp, hist_impl="pallas", any_cat=False,
            interpret=True, jit=False, wave_size=4, stochastic=False,
            quantized=True, strategy=strategy)
        wrapped = shard_map_compat(
            lambda X_T, g, h, m, nb, ic, hn, mono, cp, fm: grow(
                X_T, g, h, m, nb, ic, hn, mono, cp, (), fm),
            mesh=mesh,
            in_specs=(P(None, ax), P(ax), P(ax), P(ax), P(), P(), P(),
                      P(), P(), P()),
            out_specs=DataParallelTreeLearner._tree_specs(ax))
        collectives_reset()
        jax.make_jaxpr(lambda *a: wrapped(*a))(*args)
        out[mode] = collectives_snapshot()
    collectives_reset()

    def per_pass(snap, site):
        rec = snap.get(site)
        return rec["bytes"] / rec["count"] if rec else None

    sc = per_pass(out["scatter"], "data_parallel/wave/hist_reduce_scatter")
    ar = per_pass(out["allreduce"], "data_parallel/wave/hist_psum")
    vo = per_pass(out["voting"], "voting_parallel/wave/voted_hist_psum")
    vo_ids = per_pass(out["voting"], "voting_parallel/wave/vote_allgather")
    out["hist_bytes_per_pass"] = {"scatter": sc, "allreduce": ar,
                                  "voting": vo, "voting_ids": vo_ids}
    out["hist_bytes_ratio_allreduce_over_scatter"] = (
        round(ar / sc, 3) if sc and ar else None)
    # PV-Tree acceptance: voted-2k*B slices vs the full-F*B merge, PER
    # LEAF — every voted psum moves exactly sel*B*3 ints per candidate
    # leaf against the allreduce merge's F*B*3, so the per-leaf ratio is
    # 2k/F.  Derive the per-leaf payloads from the tallied totals (both
    # must divide exactly; a full-F histogram leaking into the voting
    # program breaks the divisibility and fails the gate), and record
    # the raw per-pass total ratio too — the voting program psums BOTH
    # children where allreduce psums the smaller child only, so its
    # per-pass total carries more (cheap) leaves.
    sel = min(2 * top_k, f)
    leaf_vo = sel * b * 3 * 4       # voted bytes per candidate leaf
    leaf_ar = f * b * 3 * 4         # full-merge bytes per leaf
    vo_tot = out["voting"].get("voting_parallel/wave/voted_hist_psum",
                               {}).get("bytes", 0)
    ar_tot = out["allreduce"].get("data_parallel/wave/hist_psum",
                                  {}).get("bytes", 0)
    ratio_budget = sel / f
    per_leaf_ratio = leaf_vo / leaf_ar
    out["hist_bytes_ratio_voting_over_allreduce_total"] = (
        round(vo / ar, 4) if vo and ar else None)
    out["hist_bytes_per_leaf"] = {"voting": leaf_vo, "allreduce": leaf_ar,
                                  "ratio": round(per_leaf_ratio, 4)}
    out["voting_ratio_ok"] = bool(
        vo_tot and ar_tot and vo_tot % leaf_vo == 0
        and ar_tot % leaf_ar == 0
        and per_leaf_ratio <= ratio_budget + 1e-9)
    out["voting_ratio_budget_2k_over_f"] = ratio_budget
    return out


def contract_sweep_per_w(ws=(4, 8, 64)) -> dict:
    """Re-parameterized contract sweep: run the full rule matrix (the
    collective budgets + the SPMD-safety pair) over the DP configs at
    W in ``ws`` — real virtual-device submeshes up to the attached
    count, trace-only AbstractMesh past it (W=64).  One declaration set
    covers every W; this sweep proves it per push (ROADMAP item 1's
    "pod path machine-checked like the single-host one")."""
    from lightgbm_tpu.analysis import lint
    from lightgbm_tpu.analysis.lint import ALL_RULES
    from lightgbm_tpu.analysis.rules import run_rules

    out = {"schema": "contracts-per-w-v1",
           "environment": lint.environment_info(),
           "worlds": {}}
    for w in ws:
        entry = {}
        for cfg in ("dp_scatter", "spec_ramp", "voting"):
            t0 = time.perf_counter()
            unit = lint.build_unit(cfg, nshards=w)
            vs = run_rules([unit], rules=ALL_RULES)
            entry[cfg] = {
                "ok": not vs,
                "violations": [v.to_json() for v in vs],
                "collectives": {site: dict(rec) for site, rec in
                                sorted(unit.collectives.items())},
                "trace_seconds": round(time.perf_counter() - t0, 2),
            }
        out["worlds"][f"W{w}"] = entry
    out["ok"] = all(c["ok"] for e in out["worlds"].values()
                    for c in e.values())
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--out", default="multichip.json")
    ap.add_argument("--per-w-out", default="contracts-per-w.json",
                    help="per-world-size contract sweep report "
                         "(W=4/8 virtual devices, W=64 trace-only)")
    ns = ap.parse_args()

    rec = {"schema": "multichip-dryrun-v1", "n_devices": ns.devices,
           "ok": False}
    t0 = time.perf_counter()
    try:
        import __graft_entry__
        __graft_entry__.dryrun_multichip(ns.devices)
        rec["ok"] = True
    except Exception:  # noqa: BLE001 — the artifact must always be written
        rec["error"] = traceback.format_exc(limit=20)
    rec["dryrun_seconds"] = round(time.perf_counter() - t0, 2)
    try:
        rec["collectives"] = collective_bytes_snapshot(ns.devices)
    except Exception:  # noqa: BLE001
        rec["collectives_error"] = traceback.format_exc(limit=20)
    per_w_ok = True
    try:
        per_w = contract_sweep_per_w()
        per_w_ok = per_w["ok"]
        with open(ns.per_w_out, "w") as fh:
            json.dump(per_w, fh, indent=2, default=str)
    except Exception:  # noqa: BLE001
        per_w_ok = False
        with open(ns.per_w_out, "w") as fh:
            json.dump({"schema": "contracts-per-w-v1", "ok": False,
                       "error": traceback.format_exc(limit=20)}, fh,
                      indent=2)
    rec["contracts_per_w_ok"] = per_w_ok
    voting_ok = rec.get("collectives", {}).get("voting_ratio_ok", False)
    with open(ns.out, "w") as fh:
        json.dump(rec, fh, indent=2, default=str)
    print(json.dumps({k: rec[k] for k in ("ok", "dryrun_seconds")} |
                     {"ratio": rec.get("collectives", {}).get(
                         "hist_bytes_ratio_allreduce_over_scatter"),
                      "voting_ratio_per_leaf": rec.get(
                          "collectives", {}).get(
                          "hist_bytes_per_leaf", {}).get("ratio"),
                      "voting_ratio_ok": voting_ok,
                      "contracts_per_w_ok": per_w_ok}))
    return 0 if rec["ok"] and per_w_ok and voting_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
