import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
n, W, B = 145408, 25, 256
rng = np.random.RandomState(0)
member = jnp.asarray(rng.rand(W, B) < 0.5)
cols = jnp.asarray(rng.randint(0, 250, (W, n)).astype(np.uint8))

def t(tag, fn, *a):
    out = fn(*a); float(jnp.sum(out.astype(jnp.float32)))
    t0 = time.perf_counter()
    for _ in range(20): out = fn(*a)
    float(jnp.sum(out.astype(jnp.float32)))
    print(f"{tag}: {(time.perf_counter()-t0)/20*1e3:.2f} ms", flush=True)

t("bool gather", jax.jit(lambda m, c: jnp.take_along_axis(m, c.astype(jnp.int32), 1)), member, cols)
t("f32 gather ", jax.jit(lambda m, c: jnp.take_along_axis(m.astype(jnp.float32), c.astype(jnp.int32), 1) > 0.5), member, cols)
t("i32 gather ", jax.jit(lambda m, c: jnp.take_along_axis(m.astype(jnp.int32), c.astype(jnp.int32), 1) > 0), member, cols)

# matmul one-hot-free: dot member f32 (W,B) with per-bin compare accumulated
# via 8-bit decomposition: col bit b of value v... instead: byte-table via
# bitpack: member bits packed to (W, 8) u32 words + extract
def bitpack(m):
    w = m.reshape(W, 32, 8)
    p2 = (2 ** jnp.arange(8, dtype=jnp.uint32))
    return jnp.sum(w.astype(jnp.uint32) * p2, axis=2)  # (W, 32) bytes
@jax.jit
def byte_gather(m, c):
    bytes_ = bitpack(m).astype(jnp.int32)      # (W, 32)
    hi = (c >> 3).astype(jnp.int32)            # (W, N) byte index
    lo = (c & 7).astype(jnp.int32)
    by = jnp.take_along_axis(bytes_, hi, 1)    # (W, N) gather from 32-wide
    return ((by >> lo) & 1) > 0
t("byte gather", byte_gather, member, cols)

colv = jnp.asarray(rng.randint(0, 250, n).astype(np.uint8))  # one cat column

@jax.jit
def embed_gather(m, cv):
    # (B, W) table, N row-indices -> (N, W): embedding-style take
    return jnp.take(m.astype(jnp.int8).T, cv.astype(jnp.int32), axis=0)
t("embed gather (N rows from (B,W))", embed_gather, member, colv)

@jax.jit
def onehot_dot(m, cv):
    oh = jax.nn.one_hot(cv.astype(jnp.int32), B, dtype=jnp.bfloat16)
    return jax.lax.dot_general(oh, m.astype(jnp.bfloat16).T,
                               (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
t("onehot dot (N,B)@(B,W)", onehot_dot, member, colv)

@jax.jit
def flat_take(m, c):
    flat_idx = (jnp.arange(W, dtype=jnp.int32)[:, None] * B +
                c.astype(jnp.int32))
    return jnp.take(m.astype(jnp.int8).ravel(), flat_idx, axis=0)
t("flat take (W,N) idx from (W*B,)", flat_take, member, cols)

@jax.jit
def flat_take_T(m, c):
    flat_idx = (c.T.astype(jnp.int32) * 1 +
                jnp.arange(W, dtype=jnp.int32)[None, :] * B)  # (N, W)
    return jnp.take(m.astype(jnp.int8).ravel(), flat_idx, axis=0)
t("flat take (N,W) idx", flat_take_T, member, cols)
