"""Partition data-movement strategies microbench at 1M-row chunks."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

N, W = 10_502_144, 48
CH = 1 << 20
rng = np.random.RandomState(0)
P8 = jnp.asarray(rng.randint(0, 255, (N, W)).astype(np.uint8))
P32 = jax.lax.bitcast_convert_type(P8.reshape(N, W // 4, 4), jnp.int32)
pos = jnp.asarray(rng.permutation(N)[:CH].astype(np.int32))
perm = jnp.asarray(rng.permutation(N)[:CH].astype(np.int32))
seg8 = jnp.asarray(rng.randint(0, 255, (CH, W)).astype(np.uint8))
seg32 = jax.lax.bitcast_convert_type(seg8.reshape(CH, W // 4, 4), jnp.int32)
key = jnp.asarray((rng.rand(CH) < 0.5).astype(np.uint8))


def force(out):
    return float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])


def timeit(name, fn, *args, reps=3):
    f = jax.jit(fn)
    force(f(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(*args)
    force(out)
    print(f"{name}: {(time.perf_counter() - t0) / reps * 1000:.1f} ms",
          flush=True)


timeit("scatter rows u8 (CH,48)", lambda P, p, s: P.at[p].set(s, mode="drop"),
       P8, pos, seg8)
timeit("scatter rows i32 (CH,12)", lambda P, p, s: P.at[p].set(s, mode="drop"),
       P32, pos, seg32)
timeit("gather rows u8", lambda P, p: P[p], P8, perm)
timeit("gather rows i32", lambda P, p: P[p], P32, perm)
timeit("scatter idx i32 (CH,)",
       lambda P, p, v: P.at[p].set(v, mode="drop"),
       jnp.zeros((N,), jnp.int32), pos, perm)


def sort_rows(key, seg):
    ops = [key.astype(jnp.int32)] + [seg[:, i] for i in range(seg.shape[1])]
    out = jax.lax.sort(ops, dimension=0, is_stable=True, num_keys=1)
    return out[1]


timeit("stable sort 12xi32 by 1-bit key", sort_rows, key, seg32)


def local_gather(seg, p):
    return seg[p]


timeit("local gather (CH,12) i32", local_gather, seg32,
       jnp.asarray(rng.permutation(CH).astype(np.int32)))
