"""Runtime of the partitioned grower's per-split pieces at Higgs scale."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

N, W, F, B = 10_502_144, 48, 28, 256
CH = 1 << 20
rng = np.random.RandomState(0)
P = jnp.asarray(rng.randint(0, 255, (N, W)).astype(np.uint8))


def _force(out):
    """Host-read a scalar derived from out (block_until_ready appears to
    return early through the axon tunnel)."""
    leaves = jax.tree_util.tree_leaves(out)
    return float(jnp.asarray(leaves[0]).ravel()[0])


def timeit(name, fn, *args, reps=3):
    _force(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _force(out)
    print(f"{name}: {(time.perf_counter() - t0) / reps * 1000:.1f} ms",
          flush=True)


# 1. full-N hist via chunk sweep (the root build)
from lightgbm_tpu.ops.histogram_pallas import build_histogram_pallas


@jax.jit
def hist_sweep(P, start, cnt):
    def body(i, acc):
        cstart = start + i * CH
        clamped = jnp.minimum(cstart, N - CH)
        seg = jax.lax.dynamic_slice(P, (clamped, 0), (CH, W))
        bins_rows = seg[:, :F]
        gm = jax.lax.bitcast_convert_type(seg[:, F:F + 4], jnp.float32)
        hm = jax.lax.bitcast_convert_type(seg[:, F + 4:F + 8], jnp.float32)
        bag = seg[:, F + 12].astype(jnp.float32)
        return acc + build_histogram_pallas(
            jnp.swapaxes(bins_rows, 0, 1), gm, hm, bag, num_bins=B)

    return jax.lax.fori_loop(0, cnt // CH, body,
                             jnp.zeros((F, B, 3), jnp.float32))


timeit("hist sweep full N (10 chunks)", hist_sweep, P,
       jnp.asarray(0, jnp.int32), jnp.asarray(N // CH * CH, jnp.int32))


# 2. count pass full N
@jax.jit
def count_sweep(P, start, cnt, feat):
    def body(i, acc):
        cstart = start + i * CH
        clamped = jnp.minimum(cstart, N - CH)
        seg = jax.lax.dynamic_slice(P, (clamped, 0), (CH, W))
        col = jax.lax.dynamic_slice(seg, (0, feat), (CH, 1))[:, 0]
        return acc + jnp.sum((col <= 100).astype(jnp.int32))

    return jax.lax.fori_loop(0, cnt // CH, body, jnp.asarray(0, jnp.int32))


timeit("count sweep full N", count_sweep, P, jnp.asarray(0, jnp.int32),
       jnp.asarray(N // CH * CH, jnp.int32), jnp.asarray(3, jnp.int32))


# 3. scatter pass full N
@jax.jit
def scatter_sweep(P, start, cnt, feat, nl):
    def body(i, carry):
        P_out, dl, dr = carry
        cstart = start + i * CH
        clamped = jnp.minimum(cstart, N - CH)
        seg = jax.lax.dynamic_slice(P, (clamped, 0), (CH, W))
        col = jax.lax.dynamic_slice(seg, (0, feat), (CH, 1))[:, 0].astype(
            jnp.int32)
        gl = col <= 100
        cl = jnp.cumsum(gl.astype(jnp.int32))
        cr = jnp.cumsum((~gl).astype(jnp.int32))
        pos = jnp.where(gl, start + dl + cl - 1, start + nl + dr + cr - 1)
        P_out = P_out.at[pos].set(seg, mode="drop")
        return P_out, dl + cl[-1], dr + cr[-1]

    out, _, _ = jax.lax.fori_loop(0, cnt // CH, body,
                                  (P, jnp.asarray(0, jnp.int32),
                                   jnp.asarray(0, jnp.int32)))
    return out


timeit("scatter sweep full N", scatter_sweep, P, jnp.asarray(0, jnp.int32),
       jnp.asarray(N // CH * CH, jnp.int32), jnp.asarray(3, jnp.int32),
       jnp.asarray(N // 2, jnp.int32))

# 4. candidate scan
from lightgbm_tpu.ops.split import SplitParams, best_split_per_feature

sp = SplitParams()
hist = jnp.asarray(rng.rand(F, B, 3).astype(np.float32))
psum = jnp.asarray(np.array([10.0, 1000.0, 10000.0], np.float32))
nb = jnp.full((F,), B, jnp.int32)
ic = jnp.zeros((F,), jnp.bool_)
hn = jnp.zeros((F,), jnp.bool_)


@jax.jit
def scan2(hist, psum):
    a = best_split_per_feature(hist, psum, nb, ic, hn, sp)
    b = best_split_per_feature(hist * 0.5, psum, nb, ic, hn, sp)
    return a.gain[0] + b.gain[0]


timeit("2x candidate scans", scan2, hist, psum, reps=10)
