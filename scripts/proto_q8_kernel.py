"""Probe: int8 quantized-gradient leaf-batched histogram kernel variants.

Round-4 perf work (VERDICT item 1a): the bf16 hi/lo leaves kernel packs
25 leaves x 5 channels into the 128 MXU lanes; quantized int8 gradients
need only 3 channels (g_q, h_q, count) -> 42 leaves/pass, and the i8
MXU path runs at 2x the bf16 MAC rate on v5e.  This script measures, on
the real chip:

  A. current bf16 leaves kernel (baseline)
  B. i8 kernel, w128 built in-kernel from (ch, w3)
  C. i8 kernel, w128 precomputed in HBM (N, 128) i8
  D. i8 kernel variant sweeps (kr, local accumulation)

plus integer exactness vs numpy bincount.
"""
import functools
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lightgbm_tpu.ops.histogram_pallas import (
    build_histogram_pallas_leaves, pack_weights8)

QC = 3                      # channels per leaf: g_q, h_q, count
QLEAVES = 128 // QC         # 42


def _round_up(x, m):
    return -(-x // m) * m


# ---------------------------------------------------------------------------
# Variant B: i8, w128 built in kernel
# ---------------------------------------------------------------------------

def _q8_kernel_inbuild(bins_ref, w_ref, ch_ref, out_ref, *, num_features,
                       num_bins, group, fstep):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    w = w_ref[...]                      # (R, 4) i8  [g_q, h_q, 1, 0]
    ch = ch_ref[...]                    # (R, 1) i32
    r = w.shape[0]
    b = num_bins

    # i8 elementwise mul is unsupported by Mosaic (probe bisect): do the
    # select arithmetic in i32 and pack to i8 once.
    lane = jax.lax.broadcasted_iota(jnp.int32, (r, 128), 1)
    leaf_of_lane = lane // QC
    sel = (ch == leaf_of_lane).astype(jnp.int32)         # (R, 128)
    w3 = w[:, :QC].astype(jnp.int32)
    wtile = jnp.concatenate([w3] * (128 // QC + 1), axis=1)[:, :128]
    w128 = (wtile * sel).astype(jnp.int8)

    iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, r), 0) % b

    def do(i, carry):
        f0 = i * fstep
        cols_blk = bins_ref[pl.ds(f0, fstep), :].astype(jnp.int32)
        for k in range(fstep // group):
            cols = cols_blk[k * group:(k + 1) * group]
            colrep = jnp.repeat(cols, b, axis=0)
            onehot = (colrep == iota_gb).astype(jnp.int8)
            part = jax.lax.dot_general(
                onehot, w128, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out_ref[pl.ds((f0 + k * group) * b, group * b)] += part
        return carry

    jax.lax.fori_loop(0, num_features // fstep, do, 0)


# ---------------------------------------------------------------------------
# Variant C: i8, w128 precomputed in HBM
# ---------------------------------------------------------------------------

def _q8_kernel_pre(bins_ref, w128_ref, out_ref, *, num_features,
                   num_bins, group, fstep):
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    w128 = w128_ref[...]                # (R, 128) i8
    r = w128.shape[0]
    b = num_bins
    iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, r), 0) % b

    def do(i, carry):
        f0 = i * fstep
        cols_blk = bins_ref[pl.ds(f0, fstep), :].astype(jnp.int32)
        for k in range(fstep // group):
            cols = cols_blk[k * group:(k + 1) * group]
            colrep = jnp.repeat(cols, b, axis=0)
            onehot = (colrep == iota_gb).astype(jnp.int8)
            part = jax.lax.dot_general(
                onehot, w128, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out_ref[pl.ds((f0 + k * group) * b, group * b)] += part
        return carry

    jax.lax.fori_loop(0, num_features // fstep, do, 0)


def _plan(f, num_bins):
    b = _round_up(num_bins, 64)
    group = next((g for g in (2, 4, 8) if (g * b) % 128 == 0), 1)
    while group * 2 <= f and group * 2 * b <= 512:
        group *= 2
    if group > f or (group * b) % 128 != 0:
        b = _round_up(num_bins, 128)
        group = 1
    fstep = max(group, 8)
    ft_cap = max(fstep, 8192 // b // fstep * fstep)
    ft = min(_round_up(f, fstep), ft_cap)
    f_pad = _round_up(f, ft)
    return b, group, fstep, ft, f_pad


@functools.partial(jax.jit, static_argnames=("num_bins", "kr"))
def q8_inbuild(bins_t, w4, ch, *, num_bins, kr=1024):
    f, n = bins_t.shape
    b, group, fstep, ft, f_pad = _plan(f, num_bins)
    if f_pad != f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - f), (0, 0)))
    grid = (f_pad // ft, n // kr)
    out = pl.pallas_call(
        functools.partial(_q8_kernel_inbuild, num_features=ft, num_bins=b,
                          group=group, fstep=fstep),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kr, 4), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kr, 1), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f_pad * b, 128), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * f_pad * b * n * 128,
            bytes_accessed=f_pad * n + n * 8 + f_pad * b * 512,
            transcendentals=0),
    )(bins_t, w4, ch.astype(jnp.int32)[:, None])
    out = out[:, :QLEAVES * QC].reshape(f_pad, b, QLEAVES, QC)
    return jnp.transpose(out, (2, 0, 1, 3))[:, :f, :num_bins, :]


@functools.partial(jax.jit, static_argnames=("num_bins", "kr"))
def q8_pre(bins_t, w128, *, num_bins, kr=1024):
    f, n = bins_t.shape
    b, group, fstep, ft, f_pad = _plan(f, num_bins)
    if f_pad != f:
        bins_t = jnp.pad(bins_t, ((0, f_pad - f), (0, 0)))
    grid = (f_pad // ft, n // kr)
    out = pl.pallas_call(
        functools.partial(_q8_kernel_pre, num_features=ft, num_bins=b,
                          group=group, fstep=fstep),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kr, 128), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f_pad * b, 128), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * f_pad * b * n * 128,
            bytes_accessed=f_pad * n + n * 128 + f_pad * b * 512,
            transcendentals=0),
    )(bins_t, w128)
    out = out[:, :QLEAVES * QC].reshape(f_pad, b, QLEAVES, QC)
    return jnp.transpose(out, (2, 0, 1, 3))[:, :f, :num_bins, :]


@jax.jit
def expand_w128(w4, ch):
    """(N, 128) i8 lane-expanded weights, built once per wave in XLA."""
    lane = jnp.arange(128, dtype=jnp.int32)
    sel = (ch[:, None] == (lane // QC)[None, :]).astype(jnp.int8)
    wtile = jnp.concatenate([w4[:, :QC]] * (128 // QC + 1), axis=1)[:, :128]
    return wtile * sel


def timeit(fn, *args, reps=5, **kw):
    out = fn(*args, **kw)
    _ = np.asarray(jnp.ravel(out)[:1])  # force through the axon tunnel
    t0 = time.perf_counter()
    for _i in range(reps):
        out = fn(*args, **kw)
        _ = np.asarray(jnp.ravel(out)[:1])
    return (time.perf_counter() - t0) / reps, out


def main():
    n, f, b = 4_194_304, 28, 255
    rng = np.random.RandomState(0)
    bins = rng.randint(0, b, (f, n)).astype(np.uint8)
    gq = rng.randint(-127, 128, n).astype(np.int8)
    hq = rng.randint(0, 128, n).astype(np.int8)
    ch = rng.randint(-1, QLEAVES, n).astype(np.int32)
    w4 = np.stack([gq, hq, np.ones(n, np.int8),
                   np.zeros(n, np.int8)], axis=-1)
    w4[ch < 0] = 0

    bins_d = jnp.asarray(bins)
    w4_d = jnp.asarray(w4)
    ch_d = jnp.asarray(ch)

    # A. baseline bf16 leaves kernel
    grad = rng.randn(n).astype(np.float32)
    hess = np.abs(rng.randn(n)).astype(np.float32)
    mask = np.ones(n, np.float32)
    w8 = pack_weights8(jnp.asarray(grad), jnp.asarray(hess),
                       jnp.asarray(mask))
    ch25 = np.where(ch >= 25, -1, ch).astype(np.int32)
    t_a, _ = timeit(build_histogram_pallas_leaves, bins_d, w8,
                    jnp.asarray(ch25), num_bins=b)
    print(f"A bf16 leaves (25/pass):      {t_a*1e3:8.2f} ms  "
          f"({n/t_a/1e9:.2f} Grows/s)", flush=True)

    # B. i8 in-kernel build
    try:
        t_b, hist_b = timeit(q8_inbuild, bins_d, w4_d, ch_d, num_bins=b)
        print(f"B i8 in-kernel (42/pass):     {t_b*1e3:8.2f} ms  "
              f"({n/t_b/1e9:.2f} Grows/s)", flush=True)
    except Exception as e:
        print(f"B FAILED: {type(e).__name__}: {str(e)[:500]}")
        hist_b = None

    # C. i8 precomputed w128
    try:
        t_w, w128_d = timeit(expand_w128, w4_d, ch_d)
        t_c, hist_c = timeit(q8_pre, bins_d, w128_d, num_bins=b)
        print(f"C i8 pre-w128 (42/pass):      {t_c*1e3:8.2f} ms  "
              f"({n/t_c/1e9:.2f} Grows/s)  (+{t_w*1e3:.2f} ms expand)",
              flush=True)
    except Exception as e:
        print(f"C FAILED: {type(e).__name__}: {str(e)[:500]}")
        hist_c = None

    # kr sweep on the winner
    for kr in (512, 2048, 4096):
        try:
            t, _ = timeit(q8_pre, bins_d, w128_d, num_bins=b, kr=kr)
            print(f"C kr={kr}:                  {t*1e3:8.2f} ms", flush=True)
        except Exception as e:
            print(f"C kr={kr} FAILED: {str(e)[:200]}")

    # exactness: integer histogram vs numpy bincount on a small slice
    if hist_b is not None or hist_c is not None:
        sub = slice(0, 65536)
        hist = np.asarray((hist_b if hist_b is not None else hist_c))
        ref = np.zeros((QLEAVES, f, b, QC), np.int64)
        chs = ch[sub]
        for c, wc in enumerate((gq[sub], hq[sub], np.ones(len(chs)))):
            for j in range(f):
                for q in range(QLEAVES):
                    m = chs == q
                    ref[q, j, :, c] = np.bincount(
                        bins[j, sub][m], weights=wc[m].astype(np.float64),
                        minlength=b)[:b]
        small = (q8_pre(jnp.asarray(bins[:, sub]), expand_w128(
            jnp.asarray(w4[sub]), jnp.asarray(chs)), num_bins=b)
            if hist_c is not None else
            q8_inbuild(jnp.asarray(bins[:, sub]), jnp.asarray(w4[sub]),
                       jnp.asarray(chs), num_bins=b))
        d = np.abs(np.asarray(small).astype(np.int64) - ref).max()
        print(f"exactness max abs diff vs numpy int: {d}")


if __name__ == "__main__":
    main()
