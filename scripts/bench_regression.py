"""Nightly bench-regression gate (ROADMAP item 5).

Diffs two bench-matrix-v1 artifacts — benchmarks/run.py (iters_per_sec),
benchmarks/many_models.py (models_per_sec), benchmarks/hist_kernel.py
(builds_per_sec) and benchmarks/loadtest.py (rows_per_sec / qps /
p99_ms / slo_ok) all emit the schema, each row named and
git-SHA-stamped — and exits nonzero when any matched row regresses past
the threshold (default 10%), the way trace-lint fails on contract
drift.  Three row classes:

  * throughput rows (higher is better): fail on drops > threshold;
  * latency rows (``p99_ms``/``p50_ms``/``recompiles`` with no
    throughput key — the loadtest per-bucket tail rows and the
    refresh-under-load deploy-cost rows): fail on INCREASES > threshold;
  * SLO verdict rows (``slo_ok``): fail when a previously-met objective
    is now breached (no envelope — a breach is binary).

Two non-bench artifacts are adapted into rows so the same gate judges
them: ``multihost-smoke-v1`` (the 2-process bit-identity verdicts become
SLO rows — a pass that flips to fail is a regression) and
``multichip-dryrun-v1`` (the dryrun/voting-budget verdicts become SLO
rows and the voted per-leaf histogram byte ratio becomes a lower-better
``bytes_ratio`` row, so a comms-efficiency giveback past the threshold
fails the night it lands).

Usage:
    python scripts/bench_regression.py --baseline prev.json \
        --current cur.json [--threshold 0.10] [--out diff.json]

Missing/invalid baseline exits 0 with a "no baseline" note (the first
nightly run after the gate lands has nothing to diff); rows only in one
artifact are reported but never fail the gate (configs come and go);
interpret-mode rungs (correctness proxies, not perf claims) are skipped.
"""

import argparse
import json
import os
import sys

THROUGHPUT_KEYS = ("iters_per_sec", "models_per_sec", "builds_per_sec",
                   "rows_per_sec", "qps")
LATENCY_KEYS = ("p99_ms", "p50_ms", "recompiles", "bytes_ratio")


def _adapt_rows(rec, path):
    """Rows for one artifact; multihost-smoke-v1 and multichip-dryrun-v1
    are adapted into bench-matrix rows, anything else must BE
    bench-matrix-v1."""
    schema = rec.get("schema")
    if schema == "bench-matrix-v1":
        return rec.get("rows", [])
    if schema == "multihost-smoke-v1":
        rows = [{"name": "multihost/smoke", "slo_ok": bool(rec.get("ok"))}]
        for check, val in sorted((rec.get("bit_identical") or {}).items()):
            rows.append({"name": f"multihost/{check}", "slo_ok": bool(val)})
        return rows
    if schema == "multichip-dryrun-v1":
        col = rec.get("collectives") or {}
        rows = [{"name": "multichip/dryrun", "slo_ok": bool(rec.get("ok"))},
                {"name": "multichip/contracts-per-w",
                 "slo_ok": bool(rec.get("contracts_per_w_ok"))},
                {"name": "multichip/voting-budget",
                 "slo_ok": bool(col.get("voting_ratio_ok"))}]
        ratio = (col.get("hist_bytes_per_leaf") or {}).get("ratio")
        if ratio is not None:
            rows.append({"name": "multichip/voting-bytes-per-leaf",
                         "bytes_ratio": float(ratio)})
        return rows
    raise ValueError(f"{path}: not a gate-readable artifact "
                     f"(schema={schema!r})")


def load_rows(path):
    """name -> (metric_key, value, direction) for one artifact.
    direction: "higher" | "lower" | "bool"."""
    with open(path) as fh:
        rec = json.load(fh)
    rows = {}
    for row in _adapt_rows(rec, path):
        if row.get("interpreted"):
            continue                 # correctness proxy, not a perf claim
        name = row.get("name")
        if not name:
            continue
        for key in THROUGHPUT_KEYS:
            if key in row:
                rows[name] = (key, float(row[key]), "higher")
                break
        else:
            if "slo_ok" in row:
                rows[name] = ("slo_ok", bool(row["slo_ok"]), "bool")
                continue
            for key in LATENCY_KEYS:
                if key in row:
                    rows[name] = (key, float(row[key]), "lower")
                    break
    return rec, rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--baseline", required=True)
    ap.add_argument("--current", required=True)
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="fail on throughput drops / latency rises "
                         "beyond this fraction")
    ap.add_argument("--out", default="",
                    help="optional JSON diff report path")
    ns = ap.parse_args(argv)

    if not os.path.exists(ns.baseline):
        print(json.dumps({"ok": True, "skipped": "no baseline artifact",
                          "baseline": ns.baseline}))
        return 0
    try:
        base_rec, base = load_rows(ns.baseline)
    except (ValueError, json.JSONDecodeError, OSError) as exc:
        print(json.dumps({"ok": True,
                          "skipped": f"unreadable baseline: {exc}"}))
        return 0
    try:
        cur_rec, cur = load_rows(ns.current)
    except (ValueError, json.JSONDecodeError, OSError) as exc:
        # the CI bench smoke writes an {"error": ...} fallback artifact
        # when the bench itself failed — that failure is already visible
        # upstream; the gate has nothing to judge and must not add a
        # crash on top of it
        print(json.dumps({"ok": True,
                          "skipped": f"unreadable current artifact: {exc}"}))
        return 0

    report = {
        "schema": "bench-regression-v1",
        "threshold": ns.threshold,
        "baseline_sha": base_rec.get("git_sha"),
        "current_sha": cur_rec.get("git_sha"),
        "rows": [],
        "regressions": [],
        "unmatched": sorted(set(base) ^ set(cur)),
    }
    for name in sorted(set(base) & set(cur)):
        key, b, direction = base[name]
        _, c, _ = cur[name]
        if direction == "bool":
            row = {"name": name, "metric": key, "baseline": bool(b),
                   "current": bool(c), "direction": direction}
            report["rows"].append(row)
            if b and not c:          # a met objective is now breached
                report["regressions"].append(row)
            continue
        ratio = c / b if b > 0 else 1.0
        row = {"name": name, "metric": key, "baseline": b, "current": c,
               "ratio": round(ratio, 4), "direction": direction}
        report["rows"].append(row)
        if direction == "higher" and ratio < 1.0 - ns.threshold:
            report["regressions"].append(row)
        elif direction == "lower" and ratio > 1.0 + ns.threshold:
            report["regressions"].append(row)
    report["ok"] = not report["regressions"]

    if ns.out:
        with open(ns.out, "w") as fh:
            json.dump(report, fh, indent=2)
    print(json.dumps({"ok": report["ok"],
                      "compared": len(report["rows"]),
                      "regressions": report["regressions"],
                      "unmatched": report["unmatched"]}, indent=2))
    if not report["ok"]:
        worst = report["regressions"][0]
        if worst.get("direction") == "bool":
            print(f"bench regression: {worst['name']} SLO verdict "
                  f"flipped met -> breached", file=sys.stderr)
        else:
            print(f"bench regression: {worst['name']} {worst['metric']} "
                  f"{worst['baseline']:.4f} -> {worst['current']:.4f} "
                  f"({abs(1 - worst['ratio']) * 100:.1f}% "
                  f"{'drop' if worst['direction'] == 'higher' else 'rise'}"
                  f" > {ns.threshold * 100:.0f}% threshold)",
                  file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
