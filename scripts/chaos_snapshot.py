"""CI recovery-telemetry snapshot: run one crash → resume cycle with the
fault-injection layer, assert the resumed model is bit-identical to an
uninterrupted run, and dump the resilience counters
(``checkpoint_write_seconds``, ``resume_total``, ``faults_injected_total``)
plus the outcome as JSON — uploaded as the CI ``chaos`` step's artifact so
the recovery path is machine-tracked per push.

Usage: python scripts/chaos_snapshot.py [--out recovery-telemetry.json]
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="recovery-telemetry.json")
    ap.add_argument("--flight-out", default="",
                    help="copy the crash's flight-recorder JSONL tape "
                         "here (CI artifact)")
    args = ap.parse_args()

    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.resilience.faults import InjectedFault, faults
    from lightgbm_tpu.telemetry.metrics import default_registry
    lgb.set_verbosity(-1)

    rng = np.random.RandomState(0)
    X = rng.randn(600, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(600) > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "seed": 7, "bagging_fraction": 0.8, "bagging_freq": 1,
              "feature_fraction": 0.8}
    rounds, crash_at = 20, 8
    t0 = time.time()
    full = lgb.train(params, lgb.Dataset(X, y), rounds)

    flight_events = 0
    with tempfile.TemporaryDirectory() as tmp:
        ck = os.path.join(tmp, "ck")
        faults.configure(f"crash_at_iter={crash_at}")
        crashed = False
        try:
            lgb.train({**params, "checkpoint_dir": ck},
                      lgb.Dataset(X, y), rounds)
        except InjectedFault:
            crashed = True
        faults.clear()
        # the crash path dumps the flight-recorder tape next to the
        # checkpoints; ship it out as the post-mortem artifact
        tape = os.path.join(ck, "flight.jsonl")
        if os.path.exists(tape):
            with open(tape) as fh:
                flight_events = max(0, sum(1 for _ in fh) - 1)  # - header
            if args.flight_out:
                import shutil
                shutil.copyfile(tape, args.flight_out)
        resumed = lgb.train({**params, "checkpoint_dir": ck,
                             "resume": "latest"}, lgb.Dataset(X, y), rounds)

    # model_to_string excludes checkpoint_dir/resume from the params dump,
    # so the two strings must match byte-for-byte with no normalization
    bit_identical = resumed.model_to_string() == full.model_to_string()
    preds_equal = bool(np.array_equal(resumed.predict(X), full.predict(X)))

    snap = default_registry().snapshot()
    keep = ("checkpoint_write_seconds", "resume_total",
            "faults_injected_total")
    record = {
        "schema": "chaos-recovery-v1",
        "crashed_at_iteration": crash_at if crashed else None,
        "rounds": rounds,
        "resume_bit_identical_model_text": bit_identical,
        "resume_predictions_equal": preds_equal,
        "flight_recorder_events": flight_events,
        "wall_seconds": round(time.time() - t0, 2),
        "metrics": {k: snap[k] for k in keep if k in snap},
    }
    with open(args.out, "w") as fh:
        json.dump(record, fh, indent=2)
    print(json.dumps(record, indent=2))
    ok = crashed and bit_identical and preds_equal
    print(f"chaos_snapshot: {'OK' if ok else 'FAILED'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
