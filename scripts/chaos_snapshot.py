"""CI recovery-telemetry snapshot: run one crash → resume cycle with the
fault-injection layer, assert the resumed model is bit-identical to an
uninterrupted run, and dump the resilience counters
(``checkpoint_write_seconds``, ``resume_total``, ``faults_injected_total``)
plus the outcome as JSON — uploaded as the CI ``chaos`` step's artifact so
the recovery path is machine-tracked per push.

The artifact also ships a serve-side ``fleet`` block: a 2-worker
``FleetSupervisor`` cycle where ``serve_crash_after_n`` kills one worker
mid-traffic, snapshotting the fleet restart/retry counters and the
per-worker breaker table after recovery (informational — the BLOCKING
fleet gate is the ``--fleet-chaos`` loadtest step; ``--fleet 0`` skips).

A third ``delta`` block covers the continuous-learning lane: a trainer
publishing per-round deltas (``publish/``) is crashed mid-run, resumed
(the restarted publisher re-anchors the journal with a fresh BASE), and
the chain is replayed both folded and record-by-record through a
serving registry — bit-identical predictions and zero dense recompiles
required (BLOCKING; ``--delta 0`` skips).

A fourth ``zoo`` block covers hash-placed multi-tenant serving: a
2-worker fleet with ``placement=hash`` sharding six zoo tenants is hit
with a SIGKILL on the worker holding the larger placed share; the
ring must re-place the fallen tenants onto the survivor (placement
epoch bump), the survivor must cold-load and serve them from the
``zoo_dir`` resolver, and the supervisor must restart the dead worker
back to a fully-alive fleet with every tenant answering again
(BLOCKING; ``--zoo 0`` skips).

Usage: python scripts/chaos_snapshot.py [--out recovery-telemetry.json]
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fleet_chaos_block(repo: str) -> dict:
    """One worker-kill/recover cycle on a 2-worker stub-model fleet;
    returns the fleet restart/breaker telemetry for the artifact."""
    import http.client
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve.fleet import FleetSupervisor
    from lightgbm_tpu.serve.loadgen import metric_sum, parse_prometheus, \
        scrape_metrics

    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.RandomState(0)
        X = rng.randn(400, 4).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        p = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
        bst = lgb.train(p, lgb.Dataset(X, y, params=p), 5)
        model_file = os.path.join(tmp, "fleet_model.txt")
        bst.save_model(model_file)
        fleet = FleetSupervisor(
            [model_file], workers=2,
            worker_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo},
            worker_args={"warmup": "0", "max_wait_ms": "0.5"},
            first_spawn_env={0: {"LGBM_TPU_FAULTS":
                                 "serve_crash_after_n=8"}},
            probe_interval_s=0.25, backoff_base_s=0.2,
            backoff_max_s=1.0, startup_timeout_s=300.0,
            run_dir=os.path.join(tmp, "fleet"))
        fleet.start()
        try:
            body = json.dumps({"rows": X[:4].tolist()}).encode()
            codes = {}
            for _ in range(30):
                conn = http.client.HTTPConnection(
                    fleet.host, fleet.port, timeout=60)
                try:
                    conn.request("POST", "/predict", body, {
                        "Content-Type": "application/json",
                        "Content-Length": str(len(body))})
                    code = conn.getresponse().status
                    codes[code] = codes.get(code, 0) + 1
                finally:
                    conn.close()
            deadline = time.time() + 20.0
            recovered = False
            while time.time() < deadline:
                parsed = parse_prometheus(
                    scrape_metrics(fleet.host, fleet.port))
                if metric_sum(parsed,
                              "lgbm_tpu_fleet_workers_alive") == 2:
                    recovered = True
                    break
                time.sleep(0.25)
            parsed = parse_prometheus(
                scrape_metrics(fleet.host, fleet.port))
            workers = {w.name: w.snapshot() for w in fleet.workers()}
        finally:
            fleet.shutdown()
    return {
        "ok": recovered and codes.get(200, 0) >= 28,
        "recovered": recovered,
        "client_codes": {str(k): v for k, v in sorted(codes.items())},
        "fleet_restarts_total": metric_sum(
            parsed, "lgbm_tpu_fleet_restarts_total"),
        "fleet_retries_total": metric_sum(
            parsed, "lgbm_tpu_fleet_retries_total"),
        "fleet_workers_alive": metric_sum(
            parsed, "lgbm_tpu_fleet_workers_alive"),
        "fleet_workers_quarantined": metric_sum(
            parsed, "lgbm_tpu_fleet_workers_quarantined"),
        "workers": workers,
    }


def _zoo_placement_block(repo: str) -> dict:
    """Kill the worker holding the larger placed-tenant share of a
    hash-placement zoo fleet; assert the ring re-places its tenants on
    the survivor (epoch bump + cold load from ``zoo_dir``), every
    tenant keeps answering, and the fleet recovers to full strength."""
    import http.client
    import shutil
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serve.fleet import FleetSupervisor
    from lightgbm_tpu.serve.loadgen import metric_sum, parse_prometheus, \
        scrape_json, scrape_metrics

    def _post(host, port, name, rows, timeout=60.0):
        body = json.dumps({"model": name, "rows": rows}).encode()
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("POST", "/predict", body, {
                "Content-Type": "application/json",
                "Content-Length": str(len(body))})
            return conn.getresponse().status
        except OSError:
            return -1
        finally:
            conn.close()

    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.RandomState(3)
        X = rng.randn(400, 4).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float64)
        p = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
        bst = lgb.train(p, lgb.Dataset(X, y, params=p), 5)
        zdir = os.path.join(tmp, "zoo")
        os.makedirs(zdir)
        base = os.path.join(zdir, "t0.txt")
        bst.save_model(base)
        names = [f"t{i}" for i in range(6)]
        for n in names[1:]:
            shutil.copyfile(base, os.path.join(zdir, f"{n}.txt"))
        rows = X[:4].tolist()
        fleet = FleetSupervisor(
            [os.path.join(zdir, f"{n}.txt") for n in names], workers=2,
            placement="hash",
            worker_env={"JAX_PLATFORMS": "cpu", "PYTHONPATH": repo},
            worker_args={"warmup": "0", "max_wait_ms": "0.5",
                         "zoo_dir": zdir},
            probe_interval_s=0.25, backoff_base_s=0.2,
            backoff_max_s=1.0, startup_timeout_s=300.0,
            run_dir=os.path.join(tmp, "fleet"))
        fleet.start()
        try:
            lap0 = {n: _post(fleet.host, fleet.port, n, rows)
                    for n in names}
            pl0 = fleet.placement_table()
            epoch0 = pl0["epoch"]
            # the worker holding the larger placed share is the victim
            victim_name = max(pl0["workers"],
                              key=lambda w: len(pl0["workers"][w]))
            fallen = list(pl0["workers"][victim_name])
            victim = next(w for w in fleet.workers()
                          if w.name == victim_name)
            victim.proc.kill()
            killed_t = time.time()
            # re-placement: the ring's routability filter drops the dead
            # worker, so its names land on the survivor — observed as an
            # epoch bump with every fallen tenant owned elsewhere
            replaced = False
            replaced_in_s = None
            pl1 = pl0
            while time.time() - killed_t < 30.0:
                pl1 = fleet.placement_table()
                owned = {n for w, ns in pl1["workers"].items()
                         for n in ns if w != victim_name}
                if pl1["epoch"] > epoch0 and all(n in owned
                                                 for n in fallen):
                    replaced = True
                    replaced_in_s = round(time.time() - killed_t, 2)
                    break
                time.sleep(0.1)
            # the fallen tenants must answer from the survivor, which
            # cold-loads them through the zoo_dir resolver; retry until
            # the window closes (dispatch may race the death detection)
            outage_codes = {}
            deadline = time.time() + 30.0
            for n in fallen:
                code = _post(fleet.host, fleet.port, n, rows)
                while code != 200 and time.time() < deadline:
                    time.sleep(0.2)
                    code = _post(fleet.host, fleet.port, n, rows)
                outage_codes[n] = code
            # supervisor recovery: the killed worker restarts and the
            # fleet returns to full strength
            recovered = False
            deadline = time.time() + 60.0
            while time.time() < deadline:
                parsed = parse_prometheus(
                    scrape_metrics(fleet.host, fleet.port))
                if metric_sum(parsed,
                              "lgbm_tpu_fleet_workers_alive") == 2:
                    recovered = True
                    break
                time.sleep(0.25)
            lap1 = {n: _post(fleet.host, fleet.port, n, rows)
                    for n in names}
            parsed = parse_prometheus(
                scrape_metrics(fleet.host, fleet.port))
            models = scrape_json(fleet.host, fleet.port, "/models")
            pl_final = fleet.placement_table()
        finally:
            fleet.shutdown()
    all_200 = lambda lap: all(c == 200 for c in lap.values())  # noqa: E731
    return {
        "ok": bool(all_200(lap0) and fallen and replaced and
                   all_200(outage_codes) and recovered and
                   all_200(lap1)),
        "tenants": names,
        "placement_before": pl0,
        "victim": victim_name,
        "fallen_tenants": fallen,
        "replaced": replaced,
        "replaced_in_s": replaced_in_s,
        "placement_after_kill": pl1,
        "outage_codes": outage_codes,
        "recovered": recovered,
        "placement_final": pl_final,
        "final_codes": lap1,
        "models_placement": models.get("_placement"),
        "fleet_restarts_total": metric_sum(
            parsed, "lgbm_tpu_fleet_restarts_total"),
        "fleet_workers_alive": metric_sum(
            parsed, "lgbm_tpu_fleet_workers_alive"),
    }


def _delta_chain_block() -> dict:
    """Continuous-learning crash cycle: a trainer publishing per-round
    deltas is crashed mid-run, resumed (the restarted publisher
    re-anchors the journal with a fresh BASE), and the journal is then
    replayed two ways — folded wholesale and applied record-by-record
    to a serving registry — both of which must predict bit-identically
    to a cold load of the finished model.  In-envelope appends must
    splice (mode ``extend``), not rebuild."""
    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.models.model_text import model_to_string
    from lightgbm_tpu.publish.delta import DeltaJournal
    from lightgbm_tpu.publish.subscriber import load_journal
    from lightgbm_tpu.resilience.faults import InjectedFault, faults
    from lightgbm_tpu.serve.registry import ModelRegistry

    rng = np.random.RandomState(1)
    X = rng.randn(400, 6)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(float)
    p = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    rounds, crash_at = 6, 4
    with tempfile.TemporaryDirectory() as tmp:
        jdir = os.path.join(tmp, "journal")
        ck = os.path.join(tmp, "ck")
        pp = {**p, "publish_dir": jdir, "publish_every": 1,
              "checkpoint_dir": ck}
        faults.configure(f"crash_at_iter={crash_at}")
        crashed = False
        try:
            lgb.train(pp, lgb.Dataset(X, y, params=pp), rounds)
        except InjectedFault:
            crashed = True
        faults.clear()
        j = DeltaJournal(jdir)
        head_mid = j.head()
        # crash_at_iter=K fires entering 0-based iteration K, so rounds
        # 1..K published before the crash; the journal must be readable
        # at exactly that boundary
        mid_ok = head_mid is not None and head_mid.round == crash_at
        resumed = lgb.train({**pp, "resume": "latest"},
                            lgb.Dataset(X, y, params=pp), rounds)
        head = j.head()
        reanchored = head is not None and head.round == rounds
        # replay path 1: fold the whole chain
        g, rnd = load_journal(jdir)
        folded = lgb.Booster(model_str=model_to_string(g))
        fold_equal = rnd == rounds and bool(
            np.array_equal(folded.predict(X[:64]),
                           resumed.predict(X[:64])))
        # replay path 2: record-by-record through a serving registry
        # (shard=8 leaves dense headroom past the re-anchored base, so
        # the appends must be in-envelope splices)
        mfile = os.path.join(tmp, "model.txt")
        resumed.save_model(mfile)
        base_path, base_round = j.base_entry()
        reg = ModelRegistry()
        reg.load("m", base_path, warmup=True, shard=8)
        Xq = X[:64].astype(np.float32)
        reg.get("m").predict(Xq)  # warm the query-shape bucket
        r0 = reg.get("m").stats.snapshot()["recompiles"]
        modes = [reg.apply_delta("m", rec)["mode"]
                 for rec in j.records_after(base_round)]
        hot_preds = np.asarray(reg.get("m").predict(Xq))
        # per-name serve stats are shared, so count recompiles before
        # the cold-load reference (whose first compile would leak in)
        recompiles = reg.get("m").stats.snapshot()["recompiles"] - r0
        cold = ModelRegistry()
        cold.load("m", mfile, warmup=False, shard=8)
        delta_equal = bool(np.array_equal(
            hot_preds, np.asarray(cold.get("m").predict(Xq))))
        zero_recompile = all(m == "extend" for m in modes) and \
            recompiles == 0
    return {
        "ok": bool(crashed and mid_ok and reanchored and fold_equal
                   and delta_equal and zero_recompile),
        "crashed": crashed,
        "journal_head_after_crash": head_mid.round if head_mid else None,
        "publisher_reanchored": reanchored,
        "fold_predictions_equal": fold_equal,
        "delta_replay_bit_identical": delta_equal,
        "apply_modes": modes,
        "delta_recompiles": recompiles,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="recovery-telemetry.json")
    ap.add_argument("--flight-out", default="",
                    help="copy the crash's flight-recorder JSONL tape "
                         "here (CI artifact)")
    ap.add_argument("--fleet", type=int, default=1,
                    help="1 (default) also runs the serve-fleet "
                         "worker-kill cycle; 0 skips it")
    ap.add_argument("--delta", type=int, default=1,
                    help="1 (default) also runs the publish-journal "
                         "crash/re-anchor/replay cycle (BLOCKING); 0 "
                         "skips it")
    ap.add_argument("--zoo", type=int, default=1,
                    help="1 (default) also runs the hash-placement zoo "
                         "worker-kill/re-placement cycle (BLOCKING); 0 "
                         "skips it")
    args = ap.parse_args()

    import numpy as np
    import lightgbm_tpu as lgb
    from lightgbm_tpu.resilience.faults import InjectedFault, faults
    from lightgbm_tpu.telemetry.metrics import default_registry
    lgb.set_verbosity(-1)

    rng = np.random.RandomState(0)
    X = rng.randn(600, 8)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.randn(600) > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "seed": 7, "bagging_fraction": 0.8, "bagging_freq": 1,
              "feature_fraction": 0.8}
    rounds, crash_at = 20, 8
    t0 = time.time()
    full = lgb.train(params, lgb.Dataset(X, y), rounds)

    flight_events = 0
    with tempfile.TemporaryDirectory() as tmp:
        ck = os.path.join(tmp, "ck")
        faults.configure(f"crash_at_iter={crash_at}")
        crashed = False
        try:
            lgb.train({**params, "checkpoint_dir": ck},
                      lgb.Dataset(X, y), rounds)
        except InjectedFault:
            crashed = True
        faults.clear()
        # the crash path dumps the flight-recorder tape next to the
        # checkpoints; ship it out as the post-mortem artifact
        tape = os.path.join(ck, "flight.jsonl")
        if os.path.exists(tape):
            with open(tape) as fh:
                flight_events = max(0, sum(1 for _ in fh) - 1)  # - header
            if args.flight_out:
                import shutil
                shutil.copyfile(tape, args.flight_out)
        resumed = lgb.train({**params, "checkpoint_dir": ck,
                             "resume": "latest"}, lgb.Dataset(X, y), rounds)

    # model_to_string excludes checkpoint_dir/resume from the params dump,
    # so the two strings must match byte-for-byte with no normalization
    bit_identical = resumed.model_to_string() == full.model_to_string()
    preds_equal = bool(np.array_equal(resumed.predict(X), full.predict(X)))

    # serve-fleet worker-kill cycle: restart/breaker telemetry rides
    # the same artifact (informational; the blocking fleet gate is the
    # --fleet-chaos loadtest CI step)
    fleet_block = None
    if args.fleet:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        try:
            fleet_block = _fleet_chaos_block(repo)
        except Exception as exc:
            print(f"chaos_snapshot: fleet block failed: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
            fleet_block = {"ok": False,
                           "error": f"{type(exc).__name__}: {exc}"}

    # continuous-learning journal cycle: crash a publishing trainer,
    # resume, and replay the re-anchored delta chain (BLOCKING — a torn
    # or diverging journal fails the snapshot)
    delta_block = None
    if args.delta:
        try:
            delta_block = _delta_chain_block()
        except Exception as exc:
            print(f"chaos_snapshot: delta block failed: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
            delta_block = {"ok": False,
                           "error": f"{type(exc).__name__}: {exc}"}

    # multi-tenant zoo cycle: kill the worker holding placed tenants,
    # assert ring re-placement + cold-load serving on the survivor and
    # full fleet recovery (BLOCKING — a tenant going dark fails it)
    zoo_block = None
    if args.zoo:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        try:
            zoo_block = _zoo_placement_block(repo)
        except Exception as exc:
            print(f"chaos_snapshot: zoo block failed: "
                  f"{type(exc).__name__}: {exc}", file=sys.stderr)
            zoo_block = {"ok": False,
                         "error": f"{type(exc).__name__}: {exc}"}

    snap = default_registry().snapshot()
    keep = ("checkpoint_write_seconds", "resume_total",
            "faults_injected_total")
    record = {
        "schema": "chaos-recovery-v1",
        "crashed_at_iteration": crash_at if crashed else None,
        "rounds": rounds,
        "resume_bit_identical_model_text": bit_identical,
        "resume_predictions_equal": preds_equal,
        "flight_recorder_events": flight_events,
        "wall_seconds": round(time.time() - t0, 2),
        "metrics": {k: snap[k] for k in keep if k in snap},
        "fleet": fleet_block,
        "delta": delta_block,
        "zoo": zoo_block,
    }
    with open(args.out, "w") as fh:
        json.dump(record, fh, indent=2)
    print(json.dumps(record, indent=2))
    ok = crashed and bit_identical and preds_equal
    if delta_block is not None:
        ok = ok and delta_block.get("ok", False)
    if zoo_block is not None:
        ok = ok and zoo_block.get("ok", False)
    print(f"chaos_snapshot: {'OK' if ok else 'FAILED'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
