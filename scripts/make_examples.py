"""Generate the checked-in examples/ datasets + CLI config files
(reference examples/ layout: TSV data with label first, train.conf /
predict.conf, .weight sidecars; data here is synthetic)."""
import os
import sys

import numpy as np

ROOT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                    "examples")


def write_tsv(path, y, X):
    with open(path, "w") as fh:
        for i in range(len(y)):
            fh.write("\t".join([f"{y[i]:g}"] +
                               [f"{v:.6g}" for v in X[i]]) + "\n")


def binary():
    d = os.path.join(ROOT, "binary_classification")
    os.makedirs(d, exist_ok=True)
    rng = np.random.RandomState(7)
    n, f = 2000, 28
    X = rng.randn(n, f)
    w = rng.randn(f) / np.sqrt(f)
    logit = X @ w + 0.4 * X[:, 0] * X[:, 1]
    y = (logit + rng.randn(n) * 0.4 > 0).astype(int)
    write_tsv(os.path.join(d, "binary.train"), y[:1600], X[:1600])
    write_tsv(os.path.join(d, "binary.test"), y[1600:], X[1600:])
    np.savetxt(os.path.join(d, "binary.train.weight"),
               np.where(y[:1600] > 0, 1.2, 1.0), fmt="%g")
    with open(os.path.join(d, "train.conf"), "w") as fh:
        fh.write("""# binary classification example (synthetic data)
task = train
boosting_type = gbdt
objective = binary
metric = binary_logloss,auc
metric_freq = 5
is_training_metric = true
max_bin = 255
data = binary.train
valid_data = binary.test
num_trees = 50
learning_rate = 0.1
num_leaves = 31
output_model = LightGBM_model.txt
""")
    with open(os.path.join(d, "predict.conf"), "w") as fh:
        fh.write("""task = predict
data = binary.test
input_model = LightGBM_model.txt
output_result = LightGBM_predict_result.txt
""")


def regression():
    d = os.path.join(ROOT, "regression")
    os.makedirs(d, exist_ok=True)
    rng = np.random.RandomState(11)
    n, f = 1500, 10
    X = rng.rand(n, f)
    y = (10 * np.sin(np.pi * X[:, 0] * X[:, 1]) + 20 * (X[:, 2] - 0.5) ** 2 +
         10 * X[:, 3] + 5 * X[:, 4] + rng.randn(n))
    write_tsv(os.path.join(d, "regression.train"), y[:1200], X[:1200])
    write_tsv(os.path.join(d, "regression.test"), y[1200:], X[1200:])
    with open(os.path.join(d, "train.conf"), "w") as fh:
        fh.write("""# regression example (synthetic friedman1-style data)
task = train
objective = regression
metric = l2
data = regression.train
valid_data = regression.test
num_trees = 60
learning_rate = 0.1
num_leaves = 31
is_training_metric = true
output_model = LightGBM_model.txt
""")
    with open(os.path.join(d, "predict.conf"), "w") as fh:
        fh.write("""task = predict
data = regression.test
input_model = LightGBM_model.txt
output_result = LightGBM_predict_result.txt
""")


def lambdarank():
    d = os.path.join(ROOT, "lambdarank")
    os.makedirs(d, exist_ok=True)
    rng = np.random.RandomState(3)
    nq, per_q, f = 80, 12, 12
    rows, labels, groups = [], [], []
    for q in range(nq):
        Xq = rng.rand(per_q, f)
        score = Xq[:, 0] * 2 + Xq[:, 1] - Xq[:, 2] + rng.randn(per_q) * 0.3
        rel = np.clip(np.digitize(score, np.quantile(score, [0.5, 0.75, 0.9])),
                      0, 4)
        rows.append(Xq)
        labels.append(rel)
        groups.append(per_q)
    X = np.concatenate(rows)
    y = np.concatenate(labels)
    ntr = 60 * per_q
    write_tsv(os.path.join(d, "rank.train"), y[:ntr], X[:ntr])
    write_tsv(os.path.join(d, "rank.test"), y[ntr:], X[ntr:])
    np.savetxt(os.path.join(d, "rank.train.query"), [per_q] * 60, fmt="%d")
    np.savetxt(os.path.join(d, "rank.test.query"), [per_q] * 20, fmt="%d")
    with open(os.path.join(d, "train.conf"), "w") as fh:
        fh.write("""# lambdarank example (synthetic queries)
task = train
objective = lambdarank
metric = ndcg
ndcg_eval_at = 1,3,5
data = rank.train
valid_data = rank.test
num_trees = 40
learning_rate = 0.1
num_leaves = 15
min_data_in_leaf = 3
output_model = LightGBM_model.txt
""")


def multiclass():
    d = os.path.join(ROOT, "multiclass_classification")
    os.makedirs(d, exist_ok=True)
    rng = np.random.RandomState(13)
    n, f = 1800, 12
    X = rng.randn(n, f)
    logits = np.stack([X[:, :4] @ (rng.randn(4)) for _ in range(5)], 1)
    y = np.argmax(logits + 0.8 * rng.randn(n, 5), axis=1).astype(int)
    write_tsv(os.path.join(d, "multiclass.train"), y[:1400], X[:1400])
    write_tsv(os.path.join(d, "multiclass.test"), y[1400:], X[1400:])
    with open(os.path.join(d, "train.conf"), "w") as fh:
        fh.write("""# multiclass classification example (synthetic data)
task = train
objective = multiclass
num_class = 5
metric = multi_logloss
data = multiclass.train
valid_data = multiclass.test
num_trees = 30
learning_rate = 0.15
num_leaves = 15
output_model = LightGBM_model.txt
""")
    with open(os.path.join(d, "predict.conf"), "w") as fh:
        fh.write("""task = predict
data = multiclass.test
input_model = LightGBM_model.txt
output_result = LightGBM_predict_result.txt
""")


if __name__ == "__main__":
    binary()
    regression()
    lambdarank()
    multiclass()
    print(f"examples written under {ROOT}")
