"""Spec-ramp commit hit-rate probe on CPU (debug.print works there).

Mimics Higgs-scale statistics at reduced n with the SAME subsample ratio
(1/8 at 2M rows): n=512K, spec_subsample=64K.
"""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["LGBM_TPU_SPEC_DEBUG"] = "1"
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from lightgbm_tpu.learner.wave import make_wave_grow_fn
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.binning import BinMapper

n, f, b = 1 << 19, 28, 255
rng = np.random.RandomState(0)
Xf = rng.randn(n, f).astype(np.float32)
w = rng.randn(f) / np.sqrt(f)
y = ((Xf @ w + 0.3*np.sin(2*Xf[:,0])*Xf[:,1] + rng.randn(n)*0.5) > 0)
bins = np.empty((f, n), np.uint8)
for j in range(f):
    from lightgbm_tpu.binning import find_bin
    m = find_bin(Xf[:, j].astype(np.float64), max_bin=b)
    bins[j] = m.value_to_bin(Xf[:, j].astype(np.float64)).astype(np.uint8)
p0 = y.mean()
grad = (p0 - y).astype(np.float32)
hess = np.full(n, p0*(1-p0), np.float32)

sp = SplitParams(min_data_in_leaf=20, any_cat=False)
grow = make_wave_grow_fn(
    num_leaves=255, num_features=f, max_bins=b, max_depth=0,
    split_params=sp, hist_impl="pallas", any_cat=False, jit=True,
    quantized=True, stochastic=False, spec_ramp=True, spec_tol=0.02,
    spec_subsample=1 << 16)
nb = jnp.full((f,), b, jnp.int32)
t = grow(jnp.asarray(bins), jnp.asarray(grad), jnp.asarray(hess),
         jnp.ones((n,), jnp.float32), nb, jnp.zeros((f,), bool),
         jnp.zeros((f,), bool), jnp.zeros((f,), jnp.int32),
         jnp.zeros((f,), jnp.float32), (), jnp.ones((f,), bool))
print("num_leaves:", int(t.num_leaves))
