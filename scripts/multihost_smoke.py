"""Multi-host smoke: 2 real ``jax.distributed`` CPU processes (gloo)
train the quantized DP wave path on pre-partitioned row shards and the
resulting MODEL TEXT must be byte-identical to a single-process 2-device
run of the same job — the pod data path's bit-identity gate (blocking in
CI next to the multichip dryrun).

Why byte-identity is achievable and therefore demanded: the W=2 world is
the same in both layouts (2 procs x 1 device vs 1 proc x 2 devices), the
row->shard split is the same contiguous halves, quantized histograms
psum in int32 (order-insensitive), stochastic rounding is off, and
distributed bin finding merges per-rank sketches that cover every row
(bin_construct_sample_cnt >> N) into the same summaries the in-core
construct sees.  Any byte of drift means a real divergence in binning,
histogram merging, split selection or text serialization.

A second phase repeats the run through the streamed ingest path — each
rank feeds ONLY its shard through a ChunkSource and binning rides the
mergeable-sketch wire format — and must match the same baseline text.

Usage: python scripts/multihost_smoke.py [--out multihost-smoke.json]
(--worker/--baseline are internal re-invocation modes).
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N, F, ROUNDS = 600, 6, 4

# pre_partition is set in BOTH layouts (inert single-process) so the
# model-text parameters block is identical byte-for-byte
PARAMS = {
    "objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
    "verbosity": -1, "tree_learner": "data", "tree_grow_mode": "wave",
    "use_quantized_grad": True, "stochastic_rounding": False,
    "quant_train_renew_leaf": True, "pre_partition": True,
}


def _make_data():
    import numpy as np
    rng = np.random.RandomState(31)
    X = rng.randn(N, F)
    y = ((X[:, 0] + 0.5 * X[:, 1] - 0.2 * X[:, 2] ** 2) > 0).astype(float)
    return X, y


def _set_cpu_devices(k):
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", k)
    except AttributeError:  # older jax: XLA_FLAGS is the portable spelling
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                                   f" --xla_force_host_platform_device_count={k}").strip()


def _run_worker(rank: int, port: str, outdir: str) -> int:
    _set_cpu_devices(1)           # 2 procs x 1 device = W=2
    import lightgbm_tpu as lgb
    lgb.distributed.init(coordinator_address="127.0.0.1:" + port,
                         num_processes=2, process_id=rank)
    from lightgbm_tpu.utils.log import set_verbosity
    set_verbosity(-1)
    X, y = _make_data()
    lo, hi = (0, N // 2) if rank == 0 else (N // 2, N)

    bst = lgb.train(dict(PARAMS), lgb.Dataset(X[lo:hi], y[lo:hi]), ROUNDS)
    with open(os.path.join(outdir, f"model_dist_{rank}.txt"), "w") as fh:
        fh.write(bst.model_to_string())

    # streamed phase: this rank's shard arrives chunk-by-chunk through
    # its own ChunkSource; sketches merge over the allgather wire
    from lightgbm_tpu.ingest.source import ArraySource
    from lightgbm_tpu.ingest.stream import StreamedDataset
    sd = StreamedDataset(ArraySource(X[lo:hi], y[lo:hi], chunk_rows=256),
                         params=dict(PARAMS))
    bst2 = lgb.train(dict(PARAMS), sd, ROUNDS)
    with open(os.path.join(outdir, f"model_stream_{rank}.txt"), "w") as fh:
        fh.write(bst2.model_to_string())
    return 0


def _run_baseline(outdir: str) -> int:
    _set_cpu_devices(2)           # 1 proc x 2 devices = same W=2 world
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.log import set_verbosity
    set_verbosity(-1)
    X, y = _make_data()
    bst = lgb.train(dict(PARAMS), lgb.Dataset(X, y), ROUNDS)
    with open(os.path.join(outdir, "model_single.txt"), "w") as fh:
        fh.write(bst.model_to_string())
    return 0


def _free_port() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return str(port)


def _launch(outdir: str) -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS="",
               PALLAS_AXON_POOL_IPS="")
    me = os.path.abspath(__file__)
    port = _free_port()
    t0 = time.perf_counter()
    procs = [subprocess.Popen(
        [sys.executable, me, "--worker", str(r), "--port", port,
         "--dir", outdir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    procs.append(subprocess.Popen(
        [sys.executable, me, "--baseline", "--dir", outdir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs, rcs = [], []
    for p in procs:
        try:
            out = p.communicate(timeout=600)[0].decode()
        except subprocess.TimeoutExpired:
            p.kill()
            out = p.communicate()[0].decode() + "\n<timeout>"
        outs.append(out)
        rcs.append(p.returncode)
    rec = {"schema": "multihost-smoke-v1", "ok": False,
           "world": {"processes": 2, "devices_per_process": 1},
           "launch_seconds": round(time.perf_counter() - t0, 2),
           "returncodes": rcs}
    if any(rc != 0 for rc in rcs):
        rec["error"] = "\n===\n".join(o[-2500:] for o in outs)
        return rec

    def read(name):
        with open(os.path.join(outdir, name), "rb") as fh:
            return fh.read()

    single = read("model_single.txt")
    checks = {}
    for tag in ("dist", "stream"):
        m0, m1 = read(f"model_{tag}_0.txt"), read(f"model_{tag}_1.txt")
        checks[f"{tag}_ranks_identical"] = m0 == m1
        checks[f"{tag}_matches_single_process"] = m0 == single
    rec["model_text_bytes"] = len(single)
    rec["bit_identical"] = checks
    rec["ok"] = all(checks.values())
    if not rec["ok"]:
        # first divergent line per failing pair, for the CI log
        import difflib
        diffs = {}
        for tag in ("dist", "stream"):
            if not checks[f"{tag}_matches_single_process"]:
                a = read(f"model_{tag}_0.txt").decode().splitlines()
                b = single.decode().splitlines()
                diffs[tag] = [ln for ln in difflib.unified_diff(
                    a, b, "distributed", "single", lineterm="", n=0)][:12]
        rec["first_divergence"] = diffs
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="multihost-smoke.json")
    ap.add_argument("--worker", type=int, default=None)
    ap.add_argument("--baseline", action="store_true")
    ap.add_argument("--port", default=None)
    ap.add_argument("--dir", default=None)
    ns = ap.parse_args()
    if ns.worker is not None:
        return _run_worker(ns.worker, ns.port, ns.dir)
    if ns.baseline:
        return _run_baseline(ns.dir)

    import tempfile
    with tempfile.TemporaryDirectory() as td:
        rec = _launch(td)
    with open(ns.out, "w") as fh:
        json.dump(rec, fh, indent=2, default=str)
    print(json.dumps({k: rec.get(k) for k in
                      ("ok", "launch_seconds", "bit_identical")}))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
