"""Round-2 q8 kernel probes: i16 compare, transposed one-hot, wch layouts."""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lightgbm_tpu.ops.histogram_pallas import build_histogram_pallas_leaves_q8

QC = 3


def _round_up(x, m):
    return -(-x // m) * m


def make_kernel(mode, b, group, ft):
    nk = ft // group

    def kern(bins_ref, wch_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        wch = wch_ref[...]
        r = wch.shape[0]
        ch = wch[:, 3:4].astype(jnp.int32)
        lane = jax.lax.broadcasted_iota(jnp.int32, (r, 128), 1)
        sel = (ch == lane // QC).astype(jnp.int32)
        w3 = wch[:, :QC].astype(jnp.int32)
        wtile = jnp.concatenate([w3] * (128 // QC + 1), axis=1)[:, :128]
        w128 = (wtile * sel).astype(jnp.int8)

        if mode == "i16":
            iota_gb = (jax.lax.broadcasted_iota(
                jnp.int32, (group * b, r), 0) % b).astype(jnp.int16)
            for k in range(nk):
                cols = bins_ref[k * group:(k + 1) * group, :].astype(
                    jnp.int16)
                colrep = jnp.repeat(cols, b, axis=0)
                onehot = (colrep == iota_gb).astype(jnp.int8)
                part = jax.lax.dot_general(
                    onehot, w128, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                out_ref[k * group * b:(k + 1) * group * b] += part
        elif mode == "tr":
            # transposed: onehotT (R, B) via lane-iota compare, dot
            # contracting lhs dim 0 (per feature)
            iota_l = jax.lax.broadcasted_iota(jnp.int32, (r, b), 1)
            for k in range(ft):
                col = bins_ref[k:k + 1, :].astype(jnp.int32)   # (1, R)
                oht = (col.T == iota_l).astype(jnp.int8)       # (R, B)
                part = jax.lax.dot_general(
                    oht, w128, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)          # (B, 128)
                out_ref[k * b:(k + 1) * b] += part
        return

    return kern


@functools.partial(jax.jit, static_argnames=("num_bins", "kr", "mode",
                                             "group"))
def q8v(bins_t, wch, *, num_bins, kr=2048, mode="i16", group=8):
    f, n = bins_t.shape
    b = _round_up(num_bins, 64)
    ft = _round_up(f, max(group, 8))
    if ft != f:
        bins_t = jnp.pad(bins_t, ((0, ft - f), (0, 0)))
    grid = (1, n // kr)
    return pl.pallas_call(
        make_kernel(mode, b, group, ft),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kr, 8), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ft * b, 128), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * ft * b * n * 128,
            bytes_accessed=ft * n + n * 8 + ft * b * 512,
            transcendentals=0),
    )(bins_t, wch)


# D: feature-major wch (8, N) with rhs-contracting-dim-1 dot
def make_kernel_fm(b, group, ft):
    nk = ft // group

    def kern(bins_ref, wch_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        wch = wch_ref[...]                    # (8, R) i8
        r = wch.shape[1]
        ch = wch[3:4, :].astype(jnp.int32)    # (1, R)
        subl = jax.lax.broadcasted_iota(jnp.int32, (128, r), 0)
        sel = (ch == subl // QC).astype(jnp.int32)
        w3 = wch[:QC, :].astype(jnp.int32)    # (3, R)
        wtile = jnp.concatenate([w3] * (128 // QC + 1), axis=0)[:128]
        w128t = (wtile * sel).astype(jnp.int8)  # (128, R)
        iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, r), 0) % b

        for k in range(nk):
            cols = bins_ref[k * group:(k + 1) * group, :].astype(jnp.int32)
            colrep = jnp.repeat(cols, b, axis=0)
            onehot = (colrep == iota_gb).astype(jnp.int8)
            part = jax.lax.dot_general(
                onehot, w128t, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)     # (g*B, 128)
            out_ref[k * group * b:(k + 1) * group * b] += part
        return

    return kern


@functools.partial(jax.jit, static_argnames=("num_bins", "kr", "group"))
def q8fm(bins_t, wch_fm, *, num_bins, kr=2048, group=8):
    f, n = bins_t.shape
    b = _round_up(num_bins, 64)
    ft = _round_up(f, max(group, 8))
    if ft != f:
        bins_t = jnp.pad(bins_t, ((0, ft - f), (0, 0)))
    grid = (1, n // kr)
    return pl.pallas_call(
        make_kernel_fm(b, group, ft),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, kr), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ft * b, 128), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * ft * b * n * 128,
            bytes_accessed=ft * n + n * 8 + ft * b * 512,
            transcendentals=0),
    )(bins_t, wch_fm)


def timed(name, fn, *args, reps=10, **kw):
    try:
        out = fn(*args, **kw)
        _ = float(jnp.ravel(out)[0])
    except Exception as e:
        print(f"{name:28s} FAIL {str(e)[:90]}", flush=True)
        return None
    t0 = time.perf_counter()
    for _i in range(reps):
        out = fn(*args, **kw)
    _ = float(jnp.ravel(out)[0])
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:28s} {dt*1e3:9.2f} ms", flush=True)
    return out


def main():
    n, f, b = 10_502_144, 28, 255
    rng = np.random.RandomState(0)
    bins = rng.randint(0, b, (f, n)).astype(np.uint8)
    gq = rng.randint(-127, 128, n).astype(np.int8)
    hq = rng.randint(0, 128, n).astype(np.int8)
    ch = rng.randint(-1, 42, n).astype(np.int8)
    wch_np = np.stack([gq, hq, np.ones(n, np.int8), ch] +
                      [np.zeros(n, np.int8)] * 4, axis=-1)
    wch_np[ch < 0, :3] = 0
    bins_d = jnp.asarray(bins)
    wch = jnp.asarray(wch_np)
    wch_fm = jnp.asarray(wch_np.T.copy())

    ref = timed("A prod q8", build_histogram_pallas_leaves_q8, bins_d, wch,
                jnp.asarray(ch), num_bins=b)
    o16 = timed("B i16 cmp g8 kr2048", q8v, bins_d, wch, num_bins=b,
                mode="i16")
    timed("B i16 cmp g8 kr1024", q8v, bins_d, wch, num_bins=b, mode="i16",
          kr=1024)
    otr = timed("C transposed onehot", q8v, bins_d, wch, num_bins=b,
                mode="tr", kr=2048)
    timed("C transposed kr=4096", q8v, bins_d, wch, num_bins=b, mode="tr",
          kr=4096)
    ofm = timed("D fm wch rhs-T dot", q8fm, bins_d, wch_fm, num_bins=b)

    # correctness cross-checks on the raw (ft*b, 128) outputs
    if ref is not None:
        refq = np.asarray(ref)
        for name, o in (("B", o16), ("C", otr), ("D", ofm)):
            if o is None:
                continue
            oq = np.asarray(o)[:28 * 256].reshape(28, 256, 128)[
                :, :255, :126].reshape(28, 255, 42, 3).transpose(2, 0, 1, 3)
            d = np.abs(oq - refq).max()
            print(f"{name} max diff vs prod: {d}")


if __name__ == "__main__":
    main()
