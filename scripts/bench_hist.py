"""Micro-benchmark: histogram implementations on the real device."""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.ops.histogram import build_histogram
from lightgbm_tpu.ops.histogram_pallas import build_histogram_pallas, pad_rows


def timeit(fn, *args, reps=5, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps, out


def main():
    n, f, b = 4_194_304, 28, 255
    rng = np.random.RandomState(0)
    bins = rng.randint(0, b, (n, f)).astype(np.uint8)
    grad = rng.randn(n).astype(np.float32)
    hess = np.abs(rng.randn(n)).astype(np.float32)
    mask = (rng.rand(n) < 0.8).astype(np.float32)

    bins_d = jnp.asarray(bins)
    bins_t = jnp.asarray(bins.T.copy())
    g, h, m = jnp.asarray(grad), jnp.asarray(hess), jnp.asarray(mask)
    assert pad_rows(n) == n, pad_rows(n)

    t_pal, hist_pal = timeit(build_histogram_pallas, bins_t, g, h, m,
                             num_bins=b)
    print(f"pallas:  {t_pal*1e3:9.2f} ms  ({n/t_pal/1e9:.2f} Grows/s)")

    # f64 reference on host for exactness check
    w = (grad.astype(np.float64) * mask, hess.astype(np.float64) * mask,
         mask.astype(np.float64))
    sub = slice(0, 262144)
    ref = np.zeros((f, b, 3))
    for c, wc in enumerate(w):
        for j in range(f):
            ref[j, :, c] = np.bincount(bins[sub, j], weights=wc[sub],
                                       minlength=b)
    t_pal_s, hist_pal_s = timeit(build_histogram_pallas,
                                 jnp.asarray(bins[sub].T.copy()), g[sub],
                                 h[sub], m[sub], num_bins=b)
    err = np.max(np.abs(np.asarray(hist_pal_s) - ref) /
                 np.maximum(1.0, np.abs(ref)))
    print(f"pallas small: {t_pal_s*1e3:7.2f} ms   max rel err vs f64: {err:.2e}")

    t_oh, hist_oh = timeit(build_histogram, bins_d, g, h, m, num_bins=b,
                           impl="onehot")
    print(f"onehot:  {t_oh*1e3:9.2f} ms  ({n/t_oh/1e9:.2f} Grows/s)")
    d = np.max(np.abs(np.asarray(hist_oh) - np.asarray(hist_pal)))
    print(f"max abs diff pallas vs onehot: {d:.3e}")


if __name__ == "__main__":
    main()
