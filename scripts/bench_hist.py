"""Deprecated shim: the histogram micro-benchmark moved to
benchmarks/hist_kernel.py (bench-matrix-v1 records, impl x B x
row_block ladder).  This wrapper keeps old invocations working."""
import os
import runpy
import sys

sys.stderr.write("scripts/bench_hist.py moved to benchmarks/"
                 "hist_kernel.py; delegating\n")
runpy.run_path(os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks", "hist_kernel.py"),
    run_name="__main__")
