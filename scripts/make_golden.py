"""Generate reference-binary golden fixtures (tests/golden/).

Runs the REAL LightGBM CLI (built from /root/reference, CPU-only — see
tests/test_reference_parity.py for the build recipe) on this repo's
committed example data and on a deterministic synthetic cat+linear
dataset, and records:

  golden_binary_model.txt    reference-trained model (weighted binary)
  golden_binary_preds.txt    its predictions on examples binary.test
  golden_catlin_data.csv     synthetic dataset (40-category feature ->
                             multi-category bitset splits; linear trees)
  golden_catlin_model.txt    reference-trained model on it
  golden_catlin_preds.txt    its predictions on the same rows
  golden.json                configs + reference-side metrics

Re-run with LGBM_BIN pointing at the reference CLI binary to regenerate.
The fixtures are committed so the parity tests run without the binary.
"""
import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLD = os.path.join(REPO, "tests", "golden")
BIN = os.environ.get("LGBM_BIN", "/tmp/lgbm_build/lightgbm")
EX = os.path.join(REPO, "examples", "binary_classification")

BINARY_PARAMS = {
    "objective": "binary", "num_leaves": 31, "num_trees": 20,
    "learning_rate": 0.1, "min_data_in_leaf": 20, "max_bin": 255,
    "num_threads": 1, "force_row_wise": "true", "verbosity": -1,
}
CATLIN_PARAMS = {
    "objective": "regression", "num_leaves": 15, "num_trees": 10,
    "learning_rate": 0.15, "min_data_in_leaf": 20, "max_bin": 63,
    "categorical_feature": "3,4", "linear_tree": "true",
    "num_threads": 1, "force_row_wise": "true", "verbosity": -1,
}


def run(task_params):
    args = [BIN] + [f"{k}={v}" for k, v in task_params.items()]
    r = subprocess.run(args, capture_output=True, text=True, timeout=300)
    if r.returncode != 0:
        raise RuntimeError(f"{args}\n{r.stdout}\n{r.stderr}")
    return r.stdout


def logloss(y, p):
    p = np.clip(p, 1e-12, 1 - 1e-12)
    return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))


def auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    npos = y.sum()
    nneg = len(y) - npos
    return float((ranks[y > 0].sum() - npos * (npos + 1) / 2) /
                 (npos * nneg))


def main():
    os.makedirs(GOLD, exist_ok=True)
    meta = {"binary_params": BINARY_PARAMS, "catlin_params": CATLIN_PARAMS}

    # --- fixture A: weighted binary on the committed example data ---
    model_a = os.path.join(GOLD, "golden_binary_model.txt")
    run(dict(BINARY_PARAMS, task="train",
             data=os.path.join(EX, "binary.train"), output_model=model_a))
    preds_a = os.path.join(GOLD, "golden_binary_preds.txt")
    run({"task": "predict", "data": os.path.join(EX, "binary.test"),
         "input_model": model_a, "output_result": preds_a,
         "verbosity": -1, "num_threads": 1})
    test = np.loadtxt(os.path.join(EX, "binary.test"))
    p = np.loadtxt(preds_a)
    meta["binary_test_logloss"] = logloss(test[:, 0], p)
    meta["binary_test_auc"] = auc(test[:, 0], p)

    # --- fixture B: multi-category bitsets + linear trees ---
    rng = np.random.RandomState(123)
    n = 2000
    cat_a = rng.randint(0, 40, n)            # 40 categories -> bitsets
    cat_b = rng.randint(0, 6, n)
    num = rng.randn(n, 3)
    y = (num[:, 0] * 2.0 + np.where(cat_a % 7 < 3, 1.5, -0.5) +
         0.3 * cat_b + 0.2 * num[:, 1] * num[:, 2] +
         0.1 * rng.randn(n))
    data = np.column_stack([y, num, cat_a, cat_b])
    csv = os.path.join(GOLD, "golden_catlin_data.csv")
    np.savetxt(csv, data, delimiter=",", fmt="%.8g")
    model_b = os.path.join(GOLD, "golden_catlin_model.txt")
    run(dict(CATLIN_PARAMS, task="train", data=csv, output_model=model_b,
             header="false", label_column=0))
    preds_b = os.path.join(GOLD, "golden_catlin_preds.txt")
    run({"task": "predict", "data": csv, "input_model": model_b,
         "output_result": preds_b, "verbosity": -1, "num_threads": 1,
         "header": "false", "label_column": 0})
    pb = np.loadtxt(preds_b)
    meta["catlin_train_rmse"] = float(np.sqrt(np.mean((pb - y) ** 2)))

    with open(os.path.join(GOLD, "golden.json"), "w") as fh:
        json.dump(meta, fh, indent=1)
    print(json.dumps(meta, indent=1))


if __name__ == "__main__":
    main()
