"""Phase-level profile of full-scale training (bench triage).

Prints wall times for: data gen, Dataset construct (binning), device
upload, learner build, first update (compile), steady-state updates.
Env: ROWS (default 10.5M), TREES (default 5), LEAVES, BINS.
"""

import faulthandler
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
faulthandler.dump_traceback_later(120, repeat=True, file=sys.stderr)

T0 = time.perf_counter()


def mark(msg):
    print(f"[{time.perf_counter() - T0:8.1f}s] {msg}", flush=True)


def main():
    rows = int(os.environ.get("ROWS", 10_500_000))
    trees = int(os.environ.get("TREES", 5))
    leaves = int(os.environ.get("LEAVES", 255))
    bins = int(os.environ.get("BINS", 255))

    import jax
    import lightgbm_tpu as lgb
    from lightgbm_tpu.utils.log import set_verbosity
    set_verbosity(-1)
    mark(f"imports done (backend={jax.default_backend()})")

    rng = np.random.RandomState(0)
    f = 28
    X = rng.randn(rows, f).astype(np.float32)
    w = rng.randn(f) / np.sqrt(f)
    logit = X @ w + 0.3 * np.sin(2 * X[:, 0]) * X[:, 1]
    y = (logit + rng.randn(rows) * 0.5 > 0).astype(np.float64)
    mark("data generated")

    params = {"objective": "binary", "num_leaves": leaves, "max_bin": bins,
              "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1,
              "tree_grow_mode": os.environ.get("GROW_MODE", "auto")}
    if int(os.environ.get("QUANT", 0)):
        params.update({"use_quantized_grad": True,
                       "num_grad_quant_bins": 254,
                       "quant_train_renew_leaf": True})
    ds = lgb.Dataset(X, y, params=params)
    from lightgbm_tpu.config import Config
    ds.construct(Config(params))
    mark("dataset constructed (binning)")

    booster = lgb.Booster(params=params, train_set=ds)
    mark("booster built (learner + upload dispatched)")
    import jax.numpy as jnp
    booster._gbdt.score.block_until_ready()
    mark("initial score ready")

    booster.update()
    float(jnp.sum(booster._gbdt.score))
    mark("first update (compile + run)")

    booster.update()
    float(jnp.sum(booster._gbdt.score))
    mark("second update")

    t = time.perf_counter()
    for _ in range(trees):
        booster.update()
    float(jnp.sum(booster._gbdt.score))
    dt = time.perf_counter() - t
    mark(f"{trees} steady updates: {dt:.2f}s -> {trees / dt:.3f} iters/s")


if __name__ == "__main__":
    main()
