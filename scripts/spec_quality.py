"""Spec-ramp quality anchor: held-out AUC/logloss spec on vs off (2M x 28)."""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import set_verbosity
set_verbosity(-1)
rng = np.random.RandomState(0)
n, f = 2_200_000, 28
X = rng.randn(n, f).astype(np.float32)
w = rng.randn(f) / np.sqrt(f)
y = ((X @ w + 0.3*np.sin(2*X[:,0])*X[:,1] + rng.randn(n)*0.5) > 0).astype(np.float64)
Xtr, ytr, Xte, yte = X[:2_000_000], y[:2_000_000], X[2_000_000:], y[2_000_000:]

def auc(y, s):
    o = np.argsort(s); r = np.empty(len(s)); r[o] = np.arange(1, len(s)+1)
    pos = y > 0
    return (r[pos].sum() - pos.sum()*(pos.sum()+1)/2) / (pos.sum()*(len(y)-pos.sum()))

for spec in (True, False):
    p = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
         "learning_rate": 0.1, "verbosity": -1, "use_quantized_grad": True,
         "num_grad_quant_bins": 254, "quant_train_renew_leaf": True,
         "tpu_speculative_ramp": spec,
         "tpu_spec_tolerance": float(os.environ.get("TOL", 0.1))}
    bst = lgb.train(p, lgb.Dataset(Xtr, ytr, params=p),
                    int(os.environ.get("TREES", 30)))
    s = bst.predict(Xte, raw_score=True)
    pr = 1/(1+np.exp(-s))
    ll = -np.mean(yte*np.log(np.clip(pr,1e-9,1)) + (1-yte)*np.log(np.clip(1-pr,1e-9,1)))
    print(f"spec={spec}: held-out logloss {ll:.5f}  AUC {auc(yte, s):.5f}",
          flush=True)
