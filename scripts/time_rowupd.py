import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
n, F, W, B, L = 145408, 12, 25, 256, 63
rng = np.random.RandomState(0)
X_T = jnp.asarray(rng.randint(0, 250, (F, n)).astype(np.uint8))
feat = jnp.asarray(rng.randint(0, F, W))
member = jnp.asarray(rng.rand(W, B) < 0.5)
rl = jnp.asarray(rng.randint(0, 40, n).astype(np.uint8))
sel_leaves = jnp.asarray(rng.choice(40, W, False))
thr = jnp.asarray(rng.randint(0, 250, W))
dleft = jnp.zeros((W,), bool)
sel = jnp.ones((W,), bool)
new_ids = jnp.asarray(40 + np.arange(W))
ls = jnp.asarray(rng.rand(W) < 0.5)

def t(tag, fn, *a):
    def syn(o):
        o = o[0] if isinstance(o, tuple) else o
        return float(jnp.sum(o.astype(jnp.float32)))
    syn(fn(*a))
    t0 = time.perf_counter()
    for _ in range(20): out = fn(*a)
    syn(out)
    print(f"{tag}: {(time.perf_counter()-t0)/20*1e3:.2f} ms", flush=True)

t("take rows", jax.jit(lambda f: jnp.take(X_T, f, axis=0)), feat)

@jax.jit
def full(feat, member, rl):
    cols_w = jnp.take(X_T, feat, axis=0)
    thr_c = thr.astype(jnp.uint8)[:, None]
    nan_c = jnp.full((W, 1), 255, jnp.uint8)
    num_go = jnp.where(cols_w == nan_c, dleft[:, None], cols_w <= thr_c)
    cat_go = jnp.take_along_axis(member, cols_w.astype(jnp.int32), axis=1)
    fcat = jnp.zeros((W,), bool).at[:2].set(True)
    go_w = jnp.where(fcat[:, None], cat_go, num_go)
    sel_c = sel_leaves.astype(rl.dtype)
    match = sel[:, None] & (rl[None, :] == sel_c[:, None])
    has = jnp.any(match, axis=0)
    jhit = jnp.argmax(match, axis=0)
    go = jnp.take_along_axis(go_w, jhit[None, :], axis=0)[0]
    ch = jnp.where(has & (go == ls[jhit]), jhit.astype(jnp.int8), jnp.int8(-1))
    rl2 = jnp.where(has & ~go, new_ids[jhit].astype(rl.dtype), rl)
    return rl2, ch
t("full rowupd", full, feat, member, rl)

@jax.jit
def memb(member, cols_w):
    return jnp.take_along_axis(member, cols_w.astype(jnp.int32), axis=1)
cols_w = jnp.take(X_T, feat, axis=0)
t("membership gather", memb, member, cols_w)
