"""Microbench: two-way stable partition of packed rows — 1-bit lax.sort
(the current partitioned-grower primitive) vs one-hot MXU matmul compaction.

A stable lefts-first partition of a row chunk is a permutation; a
permutation of rows is a one-hot (R, R) @ (R, W) matmul that rides the MXU
— bf16 is exact for byte payloads (integers <= 256) and one-hot factors.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 20          # one bulk chunk of the partitioned grower
W = 48
rng = np.random.RandomState(0)
P_np = rng.randint(0, 255, (N, W)).astype(np.uint8)
key_np = (rng.rand(N) < 0.47)


def _force(out):
    leaves = jax.tree_util.tree_leaves(out)
    return float(jnp.asarray(leaves[0]).ravel()[-1])


def timeit(name, fn, *args, reps=5):
    _force(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _force(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:42s} {dt*1e3:8.2f} ms   {dt/N*1e9:6.1f} ns/row")
    return out


@jax.jit
def sort_partition(P, gl):
    """Current primitive: stable 1-bit-key multi-operand sort."""
    key = jnp.where(gl, 0, 1).astype(jnp.int32)
    cols = jax.lax.bitcast_convert_type(P.reshape(N, W // 4, 4), jnp.int32)
    ops = [key] + [cols[:, k] for k in range(W // 4)]
    out = jax.lax.sort(ops, dimension=0, is_stable=True, num_keys=1)
    return jax.lax.bitcast_convert_type(
        jnp.stack(out[1:], axis=1), jnp.uint8).reshape(N, W)


def matmul_partition(sub):
    """One-hot permutation matmul over (nb, R, W) sub-chunks."""
    @jax.jit
    def f(P, gl):
        R = sub
        nb = N // R
        Pb = P.reshape(nb, R, W).astype(jnp.bfloat16)
        glb = gl.reshape(nb, R)
        cl = jnp.cumsum(glb.astype(jnp.int32), axis=1)
        nl = cl[:, -1:]
        cr = jnp.cumsum((~glb).astype(jnp.int32), axis=1)
        dest = jnp.where(glb, cl - 1, nl + cr - 1)          # (nb, R)
        iota = jnp.arange(R, dtype=jnp.int32)
        perm = (dest[:, None, :] == iota[None, :, None]).astype(jnp.bfloat16)
        out = jax.lax.dot_general(
            perm, Pb, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)             # (nb, R, W)
        return out.astype(jnp.uint8), nl[:, 0]
    return f


@jax.jit
def matmul_partition_scan(P, gl):
    """Matmul compaction + sequential coalesce into one staging buffer
    (the full replacement for sort_partition: output is globally
    lefts-first compacted, like the sort)."""
    R = 1024
    nb = N // R
    Pb = P.reshape(nb, R, W).astype(jnp.bfloat16)
    glb = gl.reshape(nb, R)
    cl = jnp.cumsum(glb.astype(jnp.int32), axis=1)
    nl = cl[:, -1]
    cr = jnp.cumsum((~glb).astype(jnp.int32), axis=1)
    dest = jnp.where(glb, cl - 1, nl[:, None] + cr - 1)
    iota = jnp.arange(R, dtype=jnp.int32)
    perm = (dest[:, None, :] == iota[None, :, None]).astype(jnp.bfloat16)
    comp = jax.lax.dot_general(
        perm, Pb, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32).astype(jnp.uint8)
    # coalesce: lefts ascend from 0 in the L buffer; rights DESCEND from
    # the fixed top T0 of the R buffer (each store's garbage then falls
    # strictly beyond the new watermark — the ascending-rights variant
    # clobbered previously staged rights whenever a block held lefts)
    offl = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(nl)])[:-1]
    offr = jnp.concatenate([jnp.zeros(1, jnp.int32),
                            jnp.cumsum(R - nl)])[:-1]
    Lb = jnp.zeros((N + R, W), jnp.uint8)
    Rb = jnp.zeros((N + R, W), jnp.uint8)
    T0 = N + R

    def body(i, carry):
        Lb, Rb = carry
        blk = comp[i]
        Lb = jax.lax.dynamic_update_slice(Lb, blk, (offl[i], 0))
        # the block's TOP (R - nl[i]) rows are its rights; place them at
        # [T0 - offr[i] - (R - nl[i]), T0 - offr[i])
        Rb = jax.lax.dynamic_update_slice(Rb, blk, (T0 - offr[i] - R, 0))
        return Lb, Rb

    Lb, Rb = jax.lax.fori_loop(0, nb, body, (Lb, Rb))
    return Lb, Rb, jnp.sum(nl)


def main():
    P = jnp.asarray(P_np)
    gl = jnp.asarray(key_np)
    timeit("lax.sort 1-bit key (current)", sort_partition, P, gl)
    for sub in (256, 512, 1024, 2048):
        timeit(f"matmul compact sub={sub} (no coalesce)",
               matmul_partition(sub), P, gl)
    timeit("matmul compact + coalesce (full)", matmul_partition_scan, P, gl)

    # correctness: full pipeline vs sort.  Rights are stacked descending
    # (chunk-reversed order — row order within a side is free), so compare
    # the two sides as multisets of rows.
    s = np.asarray(sort_partition(P, gl))
    Lb, Rb, nl = matmul_partition_scan(P, gl)
    nl = int(nl)
    got_l = np.asarray(Lb[:nl])
    got_r = np.asarray(Rb[1024 + nl:])  # [T0 - (N - nl), T0), T0 = N+1024
    np.testing.assert_array_equal(s[:nl], got_l)   # lefts keep order

    def rowset(a):
        return np.sort(np.ascontiguousarray(a).view(
            [("", a.dtype)] * a.shape[1]).ravel())

    np.testing.assert_array_equal(rowset(s[nl:]), rowset(got_r))
    print("full-pipeline output matches lax.sort")


if __name__ == "__main__":
    main()
