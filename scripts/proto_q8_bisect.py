"""Bisect which int8 construct Mosaic rejects, with full error text."""
import functools
import sys
import traceback

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def run(name, kernel, inputs, out_shape):
    try:
        out = pl.pallas_call(kernel, out_shape=out_shape)(*inputs)
        jax.block_until_ready(out)
        print(f"{name}: OK  sum={np.asarray(out).sum()}")
    except Exception as e:
        msg = "".join(traceback.format_exception_only(type(e), e))
        print(f"{name}: FAIL\n{msg[:2000]}\n---")


def main():
    r, b = 256, 128
    rng = np.random.RandomState(0)
    a8 = jnp.asarray(rng.randint(-10, 10, (b, r)).astype(np.int8))
    w8 = jnp.asarray(rng.randint(-10, 10, (r, 128)).astype(np.int8))
    u8 = jnp.asarray(rng.randint(0, 255, (8, r)).astype(np.uint8))
    f32 = jax.ShapeDtypeStruct((b, 128), jnp.float32)
    i32 = jax.ShapeDtypeStruct((b, 128), jnp.int32)

    # 1. plain i8 x i8 -> i32 dot
    def k1(a_ref, w_ref, o_ref):
        o_ref[...] = jax.lax.dot_general(
            a_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
    run("i8 dot -> i32", k1, (a8, w8), i32)

    # 2. u8 compare vs u8 iota -> i8 -> dot
    def k2(u_ref, w_ref, o_ref):
        iota = (jax.lax.broadcasted_iota(jnp.int32, (b, r), 0)
                % 256).astype(jnp.uint8)
        cols = jnp.repeat(u_ref[...], b // 8, axis=0)
        onehot = (cols == iota).astype(jnp.int8)
        o_ref[...] = jax.lax.dot_general(
            onehot, w_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
    run("u8 cmp onehot i8 dot", k2, (u8, w8), i32)

    # 3. i32 compare -> i8 dot (compare in 32-bit, convert)
    def k3(u_ref, w_ref, o_ref):
        iota = jax.lax.broadcasted_iota(jnp.int32, (b, r), 0) % 256
        cols = jnp.repeat(u_ref[...].astype(jnp.int32), b // 8, axis=0)
        onehot = (cols == iota).astype(jnp.int8)
        o_ref[...] = jax.lax.dot_general(
            onehot, w_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
    run("i32 cmp -> i8 dot", k3, (u8, w8), i32)

    # 4. i8 x i8 -> f32 dot
    def k4(a_ref, w_ref, o_ref):
        o_ref[...] = jax.lax.dot_general(
            a_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    run("i8 dot -> f32", k4, (a8, w8), f32)

    # 5. i8 elementwise mul then dot
    def k5(a_ref, w_ref, o_ref):
        w = w_ref[...] * jnp.int8(2)
        o_ref[...] = jax.lax.dot_general(
            a_ref[...], w, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
    run("i8 mul + dot", k5, (a8, w8), i32)

    # 6. i32 accumulate +=
    def k6(a_ref, w_ref, o_ref):
        p = jax.lax.dot_general(
            a_ref[...], w_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
        o_ref[...] = jnp.zeros_like(o_ref)
        o_ref[...] += p
    run("i32 accum +=", k6, (a8, w8), i32)


if __name__ == "__main__":
    main()
