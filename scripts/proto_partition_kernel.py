"""Prototype: Pallas per-block permutation-matmul row compaction.

Replaces the 1-bit lax.sort in the partitioned grower's stage pass.  A
stable lefts/rights/invalid partition of an R-row block is a permutation;
applied as a one-hot (R, R) @ (R, W) bf16 matmul it rides the MXU and the
permutation matrix never leaves VMEM (the XLA formulation materializes it
in HBM and is no faster than the sort — scripts/time_partition.py).

Pipeline per chunk: XLA computes go_left + within-block destinations
(cheap streaming cumsums), the kernel permutes each block, XLA coalesces
the per-block runs with the staged-write trick already used by the grower.
"""
import os
import sys
import time
import functools

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 1 << 20
W = 48
rng = np.random.RandomState(0)
P_np = rng.randint(0, 255, (N, W)).astype(np.uint8)
key_np = (rng.rand(N) < 0.47)
valid_np = np.ones(N, bool)
valid_np[rng.rand(N) < 0.1] = False


def _force(out):
    leaves = jax.tree_util.tree_leaves(out)
    return float(jnp.asarray(leaves[0]).ravel()[-1])


def timeit(name, fn, *args, reps=5):
    _force(fn(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _force(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:44s} {dt*1e3:8.2f} ms   {dt/N*1e9:6.1f} ns/row")
    return out


def _permute_kernel(dest_ref, rows_ref, out_ref, *, r: int):
    dest = dest_ref[...]                      # (R, 1) int32
    rows = rows_ref[...].astype(jnp.int32).astype(jnp.bfloat16)  # (R, W)
    # perm[d, s] = 1 iff dest[s] == d ; arithmetic (no i1 relayout)
    iota = jax.lax.broadcasted_iota(jnp.int32, (r, r), 0)      # d index
    d = (dest[:, 0][None, :] - iota).astype(jnp.float32)       # (d, s)
    perm = jnp.maximum(0.0, 1.0 - jnp.abs(d)).astype(jnp.bfloat16)
    out = jax.lax.dot_general(perm, rows, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    out_ref[...] = out.astype(jnp.int32).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=("r",))
def permute_blocks(P, dest, *, r=512):
    """Apply within-block permutation dest over blocks of r rows."""
    n, w = P.shape
    grid = (n // r,)
    return pl.pallas_call(
        functools.partial(_permute_kernel, r=r),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((r, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, w), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, w), jnp.uint8),
    )(dest[:, None], P)


@functools.partial(jax.jit, static_argnames=("r",))
def kernel_partition(P, gl, valid, *, r=512):
    """Full stage-pass equivalent: per-block compact + staged coalesce.
    Returns (Lb, Rb, nl) like the grower's stage pass (lefts at [0, nl) of
    Lb, rights at [0, nr) of Rb)."""
    n, w = P.shape
    nb = n // r
    glb = gl.reshape(nb, r)
    vb = valid.reshape(nb, r)
    l_ = (glb & vb)
    r_ = ((~glb) & vb)
    cl = jnp.cumsum(l_.astype(jnp.int32), axis=1)
    cr = jnp.cumsum(r_.astype(jnp.int32), axis=1)
    ci = jnp.cumsum((~vb).astype(jnp.int32), axis=1)
    nl = cl[:, -1]
    nr = cr[:, -1]
    ni = r - nl - nr
    # block layout [lefts | invalid | rights]: lefts bottom-aligned for the
    # ascending L stack, rights top-aligned for the descending R stack
    dest = jnp.where(l_, cl - 1,
                     jnp.where(r_, (nl + ni)[:, None] + cr - 1,
                               nl[:, None] + ci - 1))
    comp = permute_blocks(P, dest.reshape(n), r=r)
    comp = comp.reshape(nb, r, w)

    offl = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(nl)])
    offr = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(nr)])
    Lb = jnp.zeros((n + r, w), jnp.uint8)
    Rb = jnp.zeros((n + 2 * r, w), jnp.uint8)
    # rights DESCEND from T0: each block's top nr[i] rows land at
    # [T0-offr[i]-nr[i], T0-offr[i]); all garbage (lefts+invalid) falls
    # strictly below the new watermark — clobber-free for any block mix
    T0 = n + 2 * r

    def body(i, carry):
        Lb, Rb = carry
        blk = comp[i]
        Lb = jax.lax.dynamic_update_slice(Lb, blk, (offl[i], 0))
        Rb = jax.lax.dynamic_update_slice(Rb, blk, (T0 - offr[i] - r, 0))
        return Lb, Rb

    Lb, Rb = jax.lax.fori_loop(0, nb, body, (Lb, Rb))
    return Lb, Rb, offl[-1], offr[-1]


@jax.jit
def sort_partition(P, gl, valid):
    key = jnp.where(gl & valid, 0, jnp.where(valid, 1, 2))
    cols = jax.lax.bitcast_convert_type(P.reshape(N, W // 4, 4), jnp.int32)
    ops = [key] + [cols[:, k] for k in range(W // 4)]
    out = jax.lax.sort(ops, dimension=0, is_stable=True, num_keys=1)
    return jax.lax.bitcast_convert_type(
        jnp.stack(out[1:], axis=1), jnp.uint8).reshape(N, W)


def main():
    P = jnp.asarray(P_np)
    gl = jnp.asarray(key_np)
    valid = jnp.asarray(valid_np)
    timeit("lax.sort 3-way (current)", sort_partition, P, gl, valid)
    for r in (256, 512, 1024):
        timeit(f"pallas permute r={r} (kernel only)",
               lambda P, d=None, rr=r: permute_blocks(
                   P, jnp.zeros(N, jnp.int32) +
                   jnp.tile(jnp.arange(rr, dtype=jnp.int32), N // rr), r=rr),
               P)
    for r in (256, 512, 1024):
        timeit(f"kernel partition full r={r}",
               functools.partial(kernel_partition, r=r), P, gl, valid)

    s = np.asarray(sort_partition(P, gl, valid))
    Lb, Rb, nl, nr = kernel_partition(P, gl, valid, r=512)
    nl, nr = int(nl), int(nr)
    np.testing.assert_array_equal(s[:nl], np.asarray(Lb[:nl]))
    got_r = np.asarray(Rb[N + 2 * 512 - nr:])  # descending stack, top T0

    def rowset(a):
        return np.sort(np.ascontiguousarray(a).view(
            [("", a.dtype)] * a.shape[1]).ravel())

    np.testing.assert_array_equal(rowset(s[nl:nl + nr]), rowset(got_r))
    print("kernel partition matches lax.sort (lefts exact, rights as set)")


if __name__ == "__main__":
    main()
