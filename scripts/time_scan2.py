"""Scan timing with categorical configurations (the matrix's shape)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.learner.serial import local_best_candidate

C, F, B = 50, 12, 256
rng = np.random.RandomState(0)
hists = jnp.asarray(rng.rand(C, F, B, 3).astype(np.float32))
sums = jnp.asarray(hists.sum(axis=(1, 2)) / F)
nb = jnp.full((F,), B, jnp.int32)
ic = jnp.zeros((F,), bool).at[10].set(True).at[11].set(True)
hn = jnp.zeros((F,), bool)
fm = jnp.ones((F,), bool)

def run(tag, sp):
    def one(h, s):
        return local_best_candidate(h, s, nb, ic, hn, fm, sp)
    fn = jax.jit(jax.vmap(one))
    out = fn(hists, sums); jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(30):
        out = fn(hists, sums)
    float(np.asarray(out[0]).sum())
    print(f"{tag}: {(time.perf_counter()-t0)/30*1e3:.2f} ms", flush=True)

run("nocat          ", SplitParams(any_cat=False))
run("cat onehot-only", SplitParams(any_cat=True))
run("cat subset all-F", SplitParams(any_cat=True, use_cat_subset=True))
run("cat subset idx  ", SplitParams(any_cat=True, use_cat_subset=True,
                                    cat_idx=(10, 11)))
