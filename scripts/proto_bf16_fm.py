"""Probe: feature-major rhs-T layout for the EXACT bf16 leaves kernel
(mirror of the q8 win; the default non-quantized path).

HISTORICAL NOTE: the production kernel ADOPTED this layout (commit
after this probe measured 120 ms vs 165 ms), so "A prod bf16" now
measures the same feature-major form as B — the 165 ms row-major
baseline lives only in PERF.md / git history."""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lightgbm_tpu.ops.histogram_pallas import (
    build_histogram_pallas_leaves, pack_weights8, _split_hi_lo)

CB = 5  # g_hi, g_lo, h_hi, h_lo, count
LEAVES = 128 // CB


def _round_up(x, m):
    return -(-x // m) * m


def make_kernel(b, group, ft):
    nk = ft // group

    def kern(bins_ref, w_ref, ch_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        w = w_ref[...]                        # (8, R) bf16 feature-major
        ch = ch_ref[...].astype(jnp.int32)    # (1, R)
        r = w.shape[1]
        subl = jax.lax.broadcasted_iota(jnp.int32, (128, r), 0)
        sel = (ch == subl // CB).astype(jnp.bfloat16)
        w5 = w[:CB, :]
        wtile = jnp.concatenate([w5] * (128 // CB + 1), axis=0)[:128]
        w128t = wtile * sel                   # (128, R) bf16
        iota_gb = jax.lax.broadcasted_iota(jnp.int32, (group * b, r), 0) % b
        for k in range(nk):
            cols = bins_ref[k * group:(k + 1) * group, :].astype(jnp.int32)
            colrep = jnp.repeat(cols, b, axis=0)
            onehot = (colrep == iota_gb).astype(jnp.bfloat16)
            part = jax.lax.dot_general(
                onehot, w128t, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            out_ref[k * group * b:(k + 1) * group * b] += part
        return

    return kern


@functools.partial(jax.jit, static_argnames=("num_bins", "kr", "group"))
def bf16_fm(bins_t, w_fm, ch, *, num_bins, kr=2048, group=4):
    f, n = bins_t.shape
    b = _round_up(num_bins, 64)
    ft = _round_up(f, max(group, 8))
    if ft != f:
        bins_t = jnp.pad(bins_t, ((0, ft - f), (0, 0)))
    grid = (1, n // kr)
    return pl.pallas_call(
        make_kernel(b, group, ft),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((8, kr), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, kr), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ft * b, 128), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * ft * b * n * 128,
            bytes_accessed=ft * n + n * 17 + ft * b * 512,
            transcendentals=0),
    )(bins_t, w_fm, ch)


def timed(name, fn, *args, reps=10, **kw):
    try:
        out = fn(*args, **kw)
        _ = float(jnp.ravel(out)[0])
    except Exception as e:
        print(f"{name:26s} FAIL {str(e)[:90]}", flush=True)
        return None
    t0 = time.perf_counter()
    for _i in range(reps):
        out = fn(*args, **kw)
    _ = float(jnp.ravel(out)[0])
    print(f"{name:26s} {(time.perf_counter()-t0)/reps*1e3:9.2f} ms",
          flush=True)
    return out


def main():
    n, f, b = 10_502_144, 28, 255
    rng = np.random.RandomState(0)
    bins = jnp.asarray(rng.randint(0, b, (f, n)).astype(np.uint8))
    grad = jnp.asarray(rng.randn(n).astype(np.float32))
    hess = jnp.asarray(np.abs(rng.randn(n)).astype(np.float32))
    mask = jnp.ones((n,), jnp.float32)
    ch_np = rng.randint(-1, LEAVES, n).astype(np.int8)
    ch25 = jnp.asarray(ch_np.astype(np.int32))

    w8 = pack_weights8(grad, hess, mask)      # (8, N) feature-major
    t_base = timed("A prod bf16 (25/pass)",
                   lambda: build_histogram_pallas_leaves(
                       bins, w8, ch25, num_bins=b))

    @jax.jit
    def pack_fm(grad, hess, mask):
        gm = grad * mask
        hm = hess * mask
        g_hi, g_lo = _split_hi_lo(gm)
        h_hi, h_lo = _split_hi_lo(hm)
        z = jnp.zeros_like(g_hi)
        return jnp.stack([g_hi, g_lo, h_hi, h_lo,
                          (mask > 0).astype(jnp.bfloat16), z, z, z], axis=0)

    w_fm = pack_fm(grad, hess, mask)
    ch1 = jnp.asarray(ch_np)[None, :]
    for g, kr in ((4, 2048), (2, 2048), (4, 4096), (8, 2048), (2, 4096),
                  (8, 4096)):
        o = timed(f"B fm rhsT g{g} kr{kr}", bf16_fm, bins, w_fm, ch1,
                  num_bins=b, group=g, kr=kr)
        if o is not None and g == 4 and kr == 2048:
            ref = build_histogram_pallas_leaves(bins, w8, ch25, num_bins=b)
            got = np.asarray(o)[:f * 256].reshape(f, 256, 128)[
                :, :b, :125].reshape(f, b, 25, 5)
            hist = np.stack([got[..., 0] + got[..., 1],
                             got[..., 2] + got[..., 3],
                             got[..., 4]], axis=-1).transpose(2, 0, 1, 3)
            print("max diff vs prod:",
                  np.abs(hist - np.asarray(ref)).max())


if __name__ == "__main__":
    main()
