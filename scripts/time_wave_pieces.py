"""In-situ piece timings of the quantized wave grower at Higgs scale.

Amortized timing: each piece runs REPS times inside one dispatch chain
with a single host sync at the end, so the axon tunnel RTT (~tens of ms)
is paid once, not per rep.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.ops.histogram_pallas import (
    Q_LEAF_CHANNELS, build_histogram_pallas_leaves,
    build_histogram_pallas_leaves_q8, pack_weights8, pad_rows)

REPS = int(os.environ.get("REPS", 10))
N = pad_rows(int(os.environ.get("ROWS", 10_500_000)))
F, B = 28, 256


def timed(name, fn, *args, reps=REPS, **kw):
    out = fn(*args, **kw)
    _ = float(jnp.ravel(out)[0])          # sync after warmup/compile
    t0 = time.perf_counter()
    outs = None
    for _i in range(reps):
        outs = fn(*args, **kw)
    _ = float(jnp.ravel(outs)[0])
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:34s} {dt*1e3:9.2f} ms", flush=True)
    return dt


def main():
    rng = np.random.RandomState(0)
    print(f"N={N}", flush=True)
    bins = jnp.asarray(rng.randint(0, 255, (F, N)).astype(np.uint8))
    grad = jnp.asarray(rng.randn(N).astype(np.float32))
    hess = jnp.asarray(np.abs(rng.randn(N)).astype(np.float32))
    mask = jnp.ones((N,), jnp.float32)
    ch = jnp.asarray(rng.randint(-1, Q_LEAF_CHANNELS, N).astype(np.int32))
    gq = rng.randint(-127, 128, N).astype(np.int8)
    hq = rng.randint(0, 128, N).astype(np.int8)
    wch_np = np.zeros((8, N), np.int8)
    wch_np[0], wch_np[1], wch_np[2] = gq, hq, 1
    wch = jnp.asarray(wch_np)
    ch8 = jnp.asarray(rng.randint(-1, Q_LEAF_CHANNELS, N).astype(np.int8))

    # 1. q8 kernel
    timed("q8 kernel (42 leaves)",
          lambda: build_histogram_pallas_leaves_q8(bins, wch, ch8,
                                                   num_bins=255))

    # 2. bf16 kernel
    w8 = pack_weights8(grad, hess, mask)
    ch25 = jnp.where(ch >= 25, -1, ch)
    timed("bf16 kernel (25 leaves)",
          lambda: build_histogram_pallas_leaves(bins, w8, ch25, num_bins=255))

    # 4. row_leaf update loop (W=42 streaming masked updates)
    W = Q_LEAF_CHANNELS
    feat = jnp.asarray(rng.randint(0, F, W).astype(np.int32))
    thr = jnp.asarray(rng.randint(0, 255, W).astype(np.int32))
    sel_leaves = jnp.asarray(rng.randint(0, 50, W).astype(np.int32))
    new_ids = jnp.asarray((np.arange(W) + 51).astype(np.int32))

    thr8 = thr.astype(jnp.uint8)
    sel8 = sel_leaves.astype(jnp.uint8)
    new8 = new_ids.astype(jnp.uint8)
    jidx = jnp.arange(W, dtype=jnp.int8)

    @jax.jit
    def row_update(rl, bins):
        chv = jnp.full((N,), -1, jnp.int8)
        for j in range(W):
            col = jax.lax.dynamic_slice(bins, (feat[j], 0), (1, N))[0]
            go_left = col <= thr8[j]
            upd = rl == sel8[j]
            chv = jnp.where(upd & go_left, jidx[j], chv)
            rl = jnp.where(upd & jnp.logical_not(go_left), new8[j], rl)
        return rl.astype(jnp.int32) + chv

    rl0 = jnp.asarray(rng.randint(0, 50, N).astype(np.uint8))
    timed("row_leaf u8 loop (W=42)", row_update, rl0, bins)

    # 5. quantize_wch per tree
    from lightgbm_tpu.ops.quantize import quantize_wch
    timed("quantize_wch", lambda: quantize_wch(
        grad, hess, mask, jnp.float32(0.01), jnp.float32(0.01),
        jax.random.PRNGKey(0), gq_max=127, hq_max=127, stochastic=True))

    # 6. renew leaf pass (1-feature histogram)
    from lightgbm_tpu.ops.histogram_pallas import build_histogram_pallas
    rl8 = (rl0 % 256).astype(jnp.uint8)[None, :]
    timed("renew pass (1-feat hist)",
          lambda: build_histogram_pallas(rl8, grad, hess, mask, num_bins=256))

    # 7. candidate scans: 84 children x (F, B, 3)
    from lightgbm_tpu.ops.split import SplitParams, best_split_per_feature
    sp = SplitParams()
    hists = jnp.asarray(rng.rand(84, F, B, 3).astype(np.float32) * 100)
    sums = hists.sum(axis=2)[:, 0, :]
    nb = jnp.full((F,), 255, jnp.int32)
    ic = jnp.zeros((F,), jnp.bool_)
    hn = jnp.zeros((F,), jnp.bool_)

    @jax.jit
    def scans(h, s):
        def one(hh, ss):
            fs = best_split_per_feature(hh, ss, nb, ic, hn, sp)
            return fs.gain.max()
        return jax.vmap(one)(h, s).sum()

    timed("candidate scans (84 children)", scans, hists, sums)


if __name__ == "__main__":
    main()
