"""Per-update timing of the EXACT benchmark-matrix multiclass config."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax.numpy as jnp
import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import set_verbosity
set_verbosity(-1)

n = int(581_000 * 0.25)
rng = np.random.RandomState(2)
Xn = rng.randn(n, 10).astype(np.float32)
cat = rng.randint(0, 40, (n, 2)).astype(np.float32)
X = np.concatenate([Xn, cat], axis=1)
logits = np.stack([Xn @ (rng.randn(10) / 3) +
                   (cat[:, 0] % 7 == c) * 1.5 for c in range(7)], 1)
y = np.argmax(logits + 0.5 * rng.randn(n, 7), axis=1).astype(np.float64)
p = {"objective": "multiclass", "num_class": 7, "num_leaves": 63,
     "max_bin": 255, "learning_rate": 0.1, "verbosity": -1,
     "boosting": "goss"}
ds = lgb.Dataset(X, y, categorical_feature=[10, 11], params=p)
b = lgb.Booster(params=p, train_set=ds)
g = b._gbdt
def sync(): return float(jnp.sum(g.score))
b.update(); sync()
for i in range(12):
    t0 = time.perf_counter()
    b.update()
    sync()
    print(f"iter {i}: {(time.perf_counter()-t0)*1e3:.0f} ms", flush=True)
