"""Per-tree median timing A/B of the speculative ramp at full Higgs scale."""
import os, sys, time, statistics
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax.numpy as jnp
import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import set_verbosity

set_verbosity(-1)
rows = int(os.environ.get("ROWS", 10_500_000))
rng = np.random.RandomState(0)
f = 28
X = rng.randn(rows, f).astype(np.float32)
w = rng.randn(f) / np.sqrt(f)
y = ((X @ w + 0.3*np.sin(2*X[:,0])*X[:,1] + rng.randn(rows)*0.5) > 0).astype(np.float64)

def mk(spec):
    p = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
         "learning_rate": 0.1, "verbosity": -1,
         "use_quantized_grad": True, "num_grad_quant_bins": 254,
         "quant_train_renew_leaf": True, "tpu_speculative_ramp": spec}
    ds = lgb.Dataset(X, y, params=p)
    b = lgb.Booster(params=p, train_set=ds)
    b.update(); b.update()
    float(jnp.sum(b._gbdt.score))
    return b

def times(b, k=22):
    out = []
    for _ in range(k):
        t0 = time.perf_counter()
        b.update()
        float(jnp.sum(b._gbdt.score))
        out.append(time.perf_counter() - t0)
    return out

ba, bb = mk(True), mk(False)
ta, tb = times(ba), times(bb)
ma, mb = statistics.median(ta), statistics.median(tb)
print(f"spec : median {ma*1e3:.0f} ms/tree  min {min(ta)*1e3:.0f}", flush=True)
print(f"plain: median {mb*1e3:.0f} ms/tree  min {min(tb)*1e3:.0f}", flush=True)
print(f"speedup median {mb/ma:.3f}  min-based {min(tb)/min(ta):.3f}", flush=True)
