"""Generate REVERSE-interchange golden fixtures: a model trained by THIS
framework, scored by the REAL reference CLI (built per
tests/test_reference_parity.py's recipe).

  golden_ours_model.txt      our saved model (binary example data)
  golden_ours_refpreds.txt   the reference binary's predictions on
                             examples/binary_classification/binary.test

The committed pair lets tests/test_reference_parity.py assert the
reverse direction (our format parsed + reproduced by the reference)
without the binary present.  Regenerate with LGBM_BIN set.
"""
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
GOLD = os.path.join(REPO, "tests", "golden")
BIN = os.environ.get("LGBM_BIN", "/tmp/lgbm_build/lightgbm")
EX = os.path.join(REPO, "examples", "binary_classification")

import jax
jax.config.update("jax_platforms", "cpu")
import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import set_verbosity

set_verbosity(-1)
train = np.loadtxt(os.path.join(EX, "binary.train"))
p = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
     "min_data_in_leaf": 20, "seed": 7}
bst = lgb.train(p, lgb.Dataset(train[:, 1:], train[:, 0]),
                num_boost_round=8)
model = os.path.join(GOLD, "golden_ours_model.txt")
bst.save_model(model)
out = os.path.join(GOLD, "golden_ours_refpreds.txt")
subprocess.run(
    [BIN, "task=predict", f"data={os.path.join(EX, 'binary.test')}",
     f"input_model={model}", f"output_result={out}", "verbosity=-1",
     "num_threads=1"], check=True, capture_output=True, timeout=300)
test = np.loadtxt(os.path.join(EX, "binary.test"))
ours = bst.predict(test[:, 1:])
theirs = np.loadtxt(out)
np.testing.assert_allclose(theirs, ours, rtol=1e-5, atol=1e-7)
print(f"wrote {model} and {out}; live parity max diff "
      f"{np.abs(theirs - ours).max():.2e}")
