import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax.numpy as jnp
import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import set_verbosity
set_verbosity(-1)

n = int(581_000 * 0.25)
rng = np.random.RandomState(2)
Xn = rng.randn(n, 10).astype(np.float32)
cat = rng.randint(0, 40, (n, 2)).astype(np.float32)
X = np.concatenate([Xn, cat], axis=1)
logits = np.stack([Xn @ (rng.randn(10) / 3) +
                   (cat[:, 0] % 7 == c) * 1.5 for c in range(7)], 1)
y = np.argmax(logits + 0.5 * rng.randn(n, 7), axis=1).astype(np.float64)

def run(tag, extra, cats=(10, 11)):
    p = {"objective": "multiclass", "num_class": 7, "max_bin": 255,
         "learning_rate": 0.1, "verbosity": -1, "boosting": "goss"}
    p.update(extra)
    ds = lgb.Dataset(X, y, categorical_feature=list(cats), params=p)
    b = lgb.Booster(params=p, train_set=ds)
    b.update(); float(jnp.sum(b._gbdt.score))
    t0 = time.perf_counter()
    for _ in range(3):
        b.update()
    float(jnp.sum(b._gbdt.score))
    print(f"{tag}: {(time.perf_counter()-t0)/3*1e3:.0f} ms/iter", flush=True)

run("L=31 cats", {"num_leaves": 31})
run("L=63 cats", {"num_leaves": 63})
run("L=63 nocat", {"num_leaves": 63}, cats=())
run("L=63 cats partition", {"num_leaves": 63, "tree_grow_mode": "partition"})
