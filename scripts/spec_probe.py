"""TPU spec-ramp commit probe: reads prov/commit counts smuggled through
split_gain[-2:] when LGBM_TPU_SPEC_DEBUG is set (debug-only clobber)."""
import os, sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["LGBM_TPU_SPEC_DEBUG"] = "1"
import numpy as np
import jax.numpy as jnp
import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import set_verbosity
set_verbosity(-1)
rng = np.random.RandomState(0)
rows, f = int(os.environ.get("ROWS", 4_000_000)), 28
X = rng.randn(rows, f).astype(np.float32)
w = rng.randn(f) / np.sqrt(f)
y = ((X @ w + 0.3*np.sin(2*X[:,0])*X[:,1] + rng.randn(rows)*0.5) > 0).astype(np.float64)
p = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
     "learning_rate": 0.1, "verbosity": -1, "use_quantized_grad": True,
     "num_grad_quant_bins": 254, "quant_train_renew_leaf": True}
b = lgb.Booster(params=p, train_set=lgb.Dataset(X, y, params=p))
for i in range(4):
    b.update()
    t = b._gbdt.models[-1]
    sg = np.asarray(t.split_gain[-2:])
    print(f"tree {i}: prov_leaves={sg[0]:.0f} commits={sg[1]:.0f} of 41",
          flush=True)
