"""Compile-time scaling of the partitioned grower in (num_leaves, N)."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from lightgbm_tpu.learner.partitioned import make_partitioned_grow_fn
from lightgbm_tpu.ops.split import SplitParams

F, B = 28, 256
cases = [(int(a), int(b)) for a, b in
         (pair.split(":") for pair in sys.argv[1].split(","))]

for L, N in cases:
    sp = SplitParams(min_data_in_leaf=20)
    grow = make_partitioned_grow_fn(
        num_leaves=L, num_features=F, max_bins=B, max_depth=-1,
        split_params=sp, hist_impl="pallas", jit=False)
    args = (jnp.zeros((N, F), jnp.uint8), jnp.zeros((N,), jnp.float32),
            jnp.ones((N,), jnp.float32), jnp.ones((N,), jnp.float32),
            jnp.full((F,), B, jnp.int32), jnp.zeros((F,), jnp.bool_),
            jnp.zeros((F,), jnp.bool_), jnp.zeros((F,), jnp.int32),
            jnp.ones((F,), jnp.bool_))
    t0 = time.perf_counter()
    lowered = jax.jit(grow).lower(*args)
    t1 = time.perf_counter()
    compiled = lowered.compile()
    t2 = time.perf_counter()
    print(f"L={L} N={N}: trace+lower {t1 - t0:.1f}s, compile {t2 - t1:.1f}s",
          flush=True)
