"""Movement microbench round 2: realistic two-run partition patterns."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

N, W = 10_502_144, 48
CH = 1 << 20
rng = np.random.RandomState(0)
P8 = jnp.asarray(rng.randint(0, 255, (N, W)).astype(np.uint8))

# two-run gather indices: sources of the left-then-right stable partition
gl = rng.rand(CH) < 0.5
src = np.concatenate([np.nonzero(gl)[0], np.nonzero(~gl)[0]]).astype(np.int32)
perm2run = jnp.asarray(src)
permrand = jnp.asarray(rng.permutation(CH).astype(np.int32))
permid = jnp.asarray(np.arange(CH, dtype=np.int32))


def force(out):
    return float(jnp.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[0])


def timeit(name, fn, *args, reps=3):
    f = jax.jit(fn)
    force(f(*args))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(*args)
    force(out)
    print(f"{name}: {(time.perf_counter() - t0) / reps * 1000:.1f} ms",
          flush=True)


timeit("gather u8 rows, identity idx", lambda P, p: P[p], P8, permid)
timeit("gather u8 rows, two-run idx", lambda P, p: P[p], P8, perm2run)
timeit("gather u8 rows, random idx", lambda P, p: P[p], P8, permrand)


# take with take_along/indexing variants
def take_dyn(P, p):
    return jnp.take(P, p, axis=0, mode="fill", fill_value=0)


timeit("jnp.take fill two-run", take_dyn, P8, perm2run)

# wider rows: same bytes as (CH/4, 192) — is cost per ROW or per BYTE?
P192 = P8.reshape(N // 4, W * 4)
timeit("gather 192B rows (CH/4), random",
       lambda P, p: P[p], P192,
       jnp.asarray(rng.permutation(N // 4)[:CH // 4].astype(np.int32)))
P768 = P8.reshape(N // 16, W * 16)
timeit("gather 768B rows (CH/16), random",
       lambda P, p: P[p], P768,
       jnp.asarray(rng.permutation(N // 16)[:CH // 16].astype(np.int32)))
