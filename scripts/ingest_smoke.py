"""Ingest footprint smoke: stream a synthetic source far beyond what
in-core construction could hold and ASSERT the host working set stays
flat (bounded by the chunk budget, not by rows).

    JAX_PLATFORMS=cpu python scripts/ingest_smoke.py \
        rows=1e7 features=8 chunk_rows=1048576 rss_cap_mb=900 train_rounds=1

Measures peak RSS (ru_maxrss) across StreamedDataset construct (sketch
pass + bin/spill pass) and an optional short chunked-training run, and
exits nonzero when the peak exceeds ``rss_cap_mb`` — a cap chosen far
below the raw matrix's ``rows * features * 8`` bytes, so an accidental
materialization (the regression class this smoke exists to catch) fails
the build immediately.  The in-core equivalent at the default geometry
would need ~6x the cap for the raw f64 matrix alone.

CI runs this in the static-analysis job next to lint-mem: lint-mem
checks the DECLARED rows-independent HBM curve statically; this smoke
checks the HOST side empirically.
"""

import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rss_mb() -> float:
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    # ru_maxrss is KiB on Linux, bytes on macOS
    return peak / (1 << 20) if sys.platform == "darwin" else peak / 1024.0


def main(argv):
    kv = {}
    for a in argv:
        if "=" in a:
            k, v = a.lstrip("-").split("=", 1)
            kv[k.replace("-", "_")] = v
    rows = int(float(kv.get("rows", 1e7)))
    features = int(kv.get("features", 8))
    chunk_rows = int(float(kv.get("chunk_rows", 1 << 20)))
    rss_cap_mb = float(kv.get("rss_cap_mb", 900))
    train_rounds = int(kv.get("train_rounds", 1))
    out_path = kv.get("out", "")

    from lightgbm_tpu.ingest import StreamedDataset, SyntheticSource, \
        train_streamed

    params = {"objective": "binary", "verbosity": -1, "max_bin": 63,
              "num_leaves": 31, "enable_bundle": False,
              "use_quantized_grad": True, "stochastic_rounding": False,
              "tree_grow_mode": "wave", "tpu_exact_endgame": False,
              "tpu_speculative_ramp": False,
              "bin_construct_sample_cnt": 200000}
    raw_gb = rows * features * 8 / 1e9
    rss0 = _rss_mb()
    report = {"rows": rows, "features": features, "chunk_rows": chunk_rows,
              "rss_cap_mb": rss_cap_mb, "raw_matrix_gb": round(raw_gb, 3),
              "rss_baseline_mb": round(rss0, 1)}
    src = SyntheticSource(rows, features, chunk_rows=chunk_rows, seed=1)
    t0 = time.perf_counter()
    sd = StreamedDataset(src, params=params).construct()
    report["construct_seconds"] = round(time.perf_counter() - t0, 1)
    report["construct_rows_per_sec"] = round(
        rows / max(1e-9, time.perf_counter() - t0), 1)
    report["rss_after_construct_mb"] = round(_rss_mb(), 1)
    report["spill_bytes"] = os.path.getsize(sd._spill_path)

    if train_rounds > 0:
        t0 = time.perf_counter()
        bst = train_streamed(params, sd, num_boost_round=train_rounds)
        report["train_seconds"] = round(time.perf_counter() - t0, 1)
        report["trees"] = len(bst._gbdt.models)
    report["rss_peak_mb"] = round(_rss_mb(), 1)
    report["ok"] = report["rss_peak_mb"] <= rss_cap_mb
    print(json.dumps(report, indent=2))
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(report, fh, indent=2)
    if not report["ok"]:
        print(f"FAIL: peak RSS {report['rss_peak_mb']} MB exceeds the "
              f"{rss_cap_mb} MB chunk-budget cap (raw matrix would be "
              f"{raw_gb:.1f} GB — something materialized O(rows) state)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
