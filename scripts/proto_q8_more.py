"""More q8 variants: bf16 compare, transposed onehot, shape sweep."""
import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

QC = 3


def make_kernel(mode, b, group, ft):
    nk = ft // group

    def kern(bins_ref, wch_ref, out_ref):
        @pl.when(pl.program_id(1) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        wch = wch_ref[...]
        r = wch.shape[0]
        ch = wch[:, 3:4].astype(jnp.int32)
        lane = jax.lax.broadcasted_iota(jnp.int32, (r, 128), 1)
        sel = (ch == lane // QC).astype(jnp.int32)
        w3 = wch[:, :QC].astype(jnp.int32)
        wtile = jnp.concatenate([w3] * (128 // QC + 1), axis=1)[:, :128]
        w128 = (wtile * sel).astype(jnp.int8)

        if mode == "bf16cmp":
            iota_gb = (jax.lax.broadcasted_iota(
                jnp.int32, (group * b, r), 0) % b).astype(jnp.bfloat16)
            for k in range(nk):
                cols = bins_ref[k * group:(k + 1) * group, :].astype(
                    jnp.int32).astype(jnp.bfloat16)
                colrep = jnp.repeat(cols, b, axis=0)
                onehot = (colrep == iota_gb).astype(jnp.int8)
                part = jax.lax.dot_general(
                    onehot, w128, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32)
                out_ref[k * group * b:(k + 1) * group * b] += part
        elif mode == "bf16dot":
            # full bf16: onehot bf16, w128 bf16 -> f32 out? out is i32;
            # cast part. Measures whether i8 dot actually beats bf16 dot.
            w128f = w128.astype(jnp.bfloat16)
            iota_gb = (jax.lax.broadcasted_iota(
                jnp.int32, (group * b, r), 0) % b).astype(jnp.bfloat16)
            for k in range(nk):
                cols = bins_ref[k * group:(k + 1) * group, :].astype(
                    jnp.int32).astype(jnp.bfloat16)
                colrep = jnp.repeat(cols, b, axis=0)
                onehot = (colrep == iota_gb).astype(jnp.bfloat16)
                part = jax.lax.dot_general(
                    onehot, w128f, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
                out_ref[k * group * b:(k + 1) * group * b] += (
                    part.astype(jnp.int32))
        return

    return kern


@functools.partial(jax.jit, static_argnames=("num_bins", "kr", "mode",
                                             "group"))
def q8(bins_t, wch, *, num_bins, kr=1024, mode="bf16cmp", group=2):
    f, n = bins_t.shape
    b = -(-num_bins // 64) * 64
    ft = -(-f // max(group, 8)) * max(group, 8)
    if ft != f:
        bins_t = jnp.pad(bins_t, ((0, ft - f), (0, 0)))
    grid = (1, n // kr)
    return pl.pallas_call(
        make_kernel(mode, b, group, ft),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ft, kr), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((kr, 8), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((ft * b, 128), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ft * b, 128), jnp.int32),
        cost_estimate=pl.CostEstimate(
            flops=2 * ft * b * n * 128,
            bytes_accessed=ft * n + n * 8 + ft * b * 512,
            transcendentals=0),
    )(bins_t, wch)


def timeit(fn, *args, reps=5, **kw):
    out = fn(*args, **kw)
    _ = np.asarray(jnp.ravel(out)[:1])
    t0 = time.perf_counter()
    for _i in range(reps):
        out = fn(*args, **kw)
        _ = np.asarray(jnp.ravel(out)[:1])
    return (time.perf_counter() - t0) / reps, out


def main():
    n, f, b = 4_194_304, 28, 255
    rng = np.random.RandomState(0)
    bins = rng.randint(0, b, (f, n)).astype(np.uint8)
    gq = rng.randint(-127, 128, n).astype(np.int8)
    hq = rng.randint(0, 128, n).astype(np.int8)
    ch = rng.randint(-1, 42, n).astype(np.int8)
    wch = np.stack([gq, hq, np.ones(n, np.int8), ch] +
                   [np.zeros(n, np.int8)] * 4, axis=-1)
    wch[ch < 0, :3] = 0
    bins_d, wch_d = jnp.asarray(bins), jnp.asarray(wch)

    for mode in ("bf16cmp", "bf16dot"):
        for group, kr in ((2, 1024), (4, 1024), (4, 2048), (8, 1024),
                          (8, 2048), (8, 4096), (16, 2048)):
            try:
                t, _ = timeit(q8, bins_d, wch_d, num_bins=b, kr=kr,
                              mode=mode, group=group)
                print(f"{mode:8s} g={group:2d} kr={kr:5d}: {t*1e3:8.2f} ms",
                      flush=True)
            except Exception as e:
                print(f"{mode:8s} g={group:2d} kr={kr:5d}: FAIL "
                      f"{str(e)[:80]}", flush=True)


if __name__ == "__main__":
    main()

