"""A/B the speculative ramp at scale in ONE process (controls tunnel drift)."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax.numpy as jnp
import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import set_verbosity

set_verbosity(-1)
rows = int(os.environ.get("ROWS", 6_000_000))
rng = np.random.RandomState(0)
f = 28
X = rng.randn(rows, f).astype(np.float32)
w = rng.randn(f) / np.sqrt(f)
y = ((X @ w + 0.3*np.sin(2*X[:,0])*X[:,1] + rng.randn(rows)*0.5) > 0).astype(np.float64)

def mk(spec):
    p = {"objective": "binary", "num_leaves": 255, "max_bin": 255,
         "learning_rate": 0.1, "verbosity": -1,
         "use_quantized_grad": True, "num_grad_quant_bins": 254,
         "quant_train_renew_leaf": True, "tpu_speculative_ramp": spec}
    ds = lgb.Dataset(X, y, params=p)
    b = lgb.Booster(params=p, train_set=ds)
    b.update(); b.update()
    float(jnp.sum(b._gbdt.score))
    return b

def run(b, k=6):
    t0 = time.perf_counter()
    for _ in range(k):
        b.update()
    float(jnp.sum(b._gbdt.score))
    return k / (time.perf_counter() - t0)

ba = mk(True)
bb = mk(False)
for i in range(3):
    ra = run(ba); rb = run(bb)
    print(f"round {i}: spec={ra:.4f} plain={rb:.4f} iters/s  ratio={ra/rb:.3f}", flush=True)
