"""Time the vmapped candidate scan (84 children, F=28, B=256) on the chip."""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from lightgbm_tpu.ops.split import SplitParams
from lightgbm_tpu.learner.serial import local_best_candidate

C, F, B = 84, 28, 256
rng = np.random.RandomState(0)
hists = jnp.asarray(rng.rand(C, F, B, 3).astype(np.float32))
sums = jnp.asarray(hists.sum(axis=(1, 2)) / F)
nb = jnp.full((F,), B, jnp.int32)
ic = jnp.zeros((F,), bool)
hn = jnp.zeros((F,), bool)
fm = jnp.ones((F,), bool)
sp = SplitParams(any_cat=False)
sp_cat = SplitParams(any_cat=True)

def run(sp):
    def one(h, s):
        return local_best_candidate(h, s, nb, ic, hn, fm, sp)
    fn = jax.jit(jax.vmap(one))
    out = fn(hists, sums)
    jax.block_until_ready(out)
    reps = 30
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(hists, sums)
    # force host copy (axon timing gotcha)
    float(np.asarray(out[0]).sum())
    return (time.perf_counter() - t0) / reps * 1e3

print(f"scan any_cat=False: {run(sp):.2f} ms")
print(f"scan any_cat=True : {run(sp_cat):.2f} ms")
