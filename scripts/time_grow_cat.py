import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax, jax.numpy as jnp
import lightgbm_tpu as lgb
from lightgbm_tpu.utils.log import set_verbosity
set_verbosity(-1)
n = 145250
rng = np.random.RandomState(2)
Xn = rng.randn(n, 10).astype(np.float32)
cat = rng.randint(0, 40, (n, 2)).astype(np.float32)
X = np.concatenate([Xn, cat], axis=1)
y = ((Xn[:, 0] + (cat[:, 0] % 3 == 1)) > 0.5).astype(np.float64)

for tag, cats in (("cats", [10, 11]), ("nocat", [])):
    p = {"objective": "binary", "num_leaves": 63, "max_bin": 255,
         "verbosity": -1}
    ds = lgb.Dataset(X, y, categorical_feature=cats, params=p)
    b = lgb.Booster(params=p, train_set=ds)
    g = b._gbdt
    b.update(); float(jnp.sum(g.score))
    grad, hess = g.objective.get_gradients(g.score)
    fmask = g._feature_mask()
    mask = jnp.ones((n,), jnp.float32)
    out = g.learner.train(g.X_dev, grad, hess, mask, feature_mask=fmask)
    jax.block_until_ready(out.num_leaves)
    t0 = time.perf_counter()
    for _ in range(15):
        out = g.learner.train(g.X_dev, grad, hess, mask, feature_mask=fmask)
    float(np.asarray(out.num_leaves))
    print(f"grow {tag}: {(time.perf_counter()-t0)/15*1e3:.0f} ms", flush=True)
